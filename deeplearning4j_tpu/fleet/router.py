"""Cache-aware fleet router: placement, health-gated membership, failover.

The first subsystem that makes N ``GenerationEngine`` replicas act as one
service (ROADMAP item 5b).  The scoring math itself lives in
``fleet/placement.py`` (stdlib-only, CI-simulatable); this module is the
live half:

* **membership** — the routing table is refreshed from the PR-18
  ``FleetAggregator`` view: a replica whose snapshots go stale or whose
  published ``/health`` verdict fails is drained from new placements
  before requests ever error against it.  Replica handles are
  ``attach``-ed explicitly (the supervisor or test wires them); the
  aggregator decides whether an attached replica is placeable.
* **failover** — a dead replica's in-queue requests (nothing streamed
  yet) are transparently retried on a survivor through the PR-5
  ``RetryPolicy`` (its transient/fatal classification, seeded backoff,
  and retry metrics), with ``dl4j_fleet_router_failovers_total{reason}``
  on record.  A request that already streamed tokens is NOT replayed —
  the client would see duplicated output — it gets the terminal error
  (the HTTP frontend turns that into the clean terminal SSE event).
  The death mark is keyed on the replica's last published
  ``(epoch, seq)``: a restart publishes a fresh epoch (which the
  aggregator re-bases exactly), clearing the mark so the replica
  rejoins automatically.
* **tracing** — every placement records a ``fleet_route`` span (scored
  candidates, chosen replica, placement reason) under the request's
  ``X-Request-Id``, which the router mints at the edge when the client
  did not.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from deeplearning4j_tpu.fleet.placement import (
    DEFAULT_OVERLOAD_FACTOR, ReplicaView, choose)
from deeplearning4j_tpu.observability.metrics import get_registry
from deeplearning4j_tpu.observability.tracing import get_tracer, new_trace_id
from deeplearning4j_tpu.resilience.retry import RetryPolicy, is_transient
from deeplearning4j_tpu.serving.admission import (
    QueueFullError, ServingError, ShuttingDownError)

logger = logging.getLogger("dl4j_tpu.fleet")

# failover reasons (the {reason} label values)
REPLICA_DEAD = "replica_dead"
DRAINING = "draining"
QUEUE_FULL = "queue_full"


class NoLiveReplicaError(ServingError):
    """Every attached replica is stale, unhealthy, drained, or dead —
    there is nowhere to place the request.  503, and FATAL for retry
    purposes: backoff inside the router cannot conjure a replica."""

    http_status = 503
    shed_reason = "no_live_replica"


def _failover_reason(exc: BaseException) -> Optional[str]:
    """Map a submit/stream failure to a failover reason, or None when it
    is the client's problem (bad request → no retry, no blame)."""
    if isinstance(exc, QueueFullError):
        return QUEUE_FULL
    if isinstance(exc, ShuttingDownError):
        return DRAINING
    if isinstance(exc, NoLiveReplicaError) or not is_transient(exc):
        return None
    return REPLICA_DEAD


def _failover_transient(exc: BaseException) -> bool:
    """Retry classification for the router's RetryPolicy: retryable is
    exactly what has a failover reason (queue-full and draining replicas
    are retryable-elsewhere even though their messages don't match the
    infra-transient patterns)."""
    return _failover_reason(exc) is not None


class _Entry:
    """One attached replica: its handle plus the router's view of it."""

    __slots__ = ("handle", "view", "dead_mark", "ok", "bad", "joined")

    def __init__(self, handle, view: ReplicaView):
        self.handle = handle
        self.view = view
        # (epoch, seq) at death observation; cleared when the published
        # stream moves past it (fresh epoch or seq advance = alive again)
        self.dead_mark: Optional[Tuple[Optional[str], int]] = None
        self.ok = 0       # finished requests (length/stop)
        self.bad = 0      # terminal errors attributed to this replica
        self.joined = False


class Placement:
    """One routing decision, as recorded in the ``fleet_route`` span."""

    __slots__ = ("replica_id", "reason", "scores", "trace_id", "n")

    def __init__(self, replica_id: str, reason: str,
                 scores: Dict[str, Dict[str, Any]], trace_id: str, n: int):
        self.replica_id = replica_id
        self.reason = reason
        self.scores = scores
        self.trace_id = trace_id
        self.n = n

    def as_dict(self) -> Dict[str, Any]:
        return {"replica": self.replica_id, "reason": self.reason,
                "trace_id": self.trace_id, "n": self.n,
                "scores": self.scores}


class FleetRouter:
    """Places generation requests across attached replicas (module
    docstring).  Thread-safe; one instance fronts the whole fleet."""

    def __init__(self, *, aggregator=None, page_size: int = 16,
                 seed: int = 0, registry=None, retry_policy=None,
                 refresh_interval_s: float = 0.25,
                 policy: str = "affinity",
                 overload_factor: float = DEFAULT_OVERLOAD_FACTOR,
                 shadow_max_pages: int = 8192):
        self.aggregator = aggregator
        self.page_size = int(page_size)
        self.seed = int(seed)
        self.policy = policy
        self.overload_factor = float(overload_factor)
        self.shadow_max_pages = int(shadow_max_pages)
        self.refresh_interval_s = float(refresh_interval_s)
        self.registry = registry or get_registry()
        # short fuse: failover should land on a survivor in well under a
        # second, not wait out the training-path default backoff
        self.retry_policy = retry_policy or RetryPolicy(
            max_retries=3, base_delay_s=0.05, max_delay_s=1.0,
            seed=self.seed, component="fleet_router",
            classify=_failover_transient, registry=self.registry)
        self._lock = threading.RLock()
        self._replicas: Dict[str, _Entry] = {}
        self._sessions: Dict[str, Dict[str, Any]] = {}
        self._split: Optional[Tuple[str, float, int]] = None
        self._n = 0                    # request index (tie/canary coins)
        self._last_refresh = 0.0
        self._m_requests = self.registry.counter(
            "dl4j_fleet_router_requests_total",
            "Requests placed, by chosen replica and placement reason",
            labels=("replica", "reason"))
        self._m_failovers = self.registry.counter(
            "dl4j_fleet_router_failovers_total",
            "Placement retries after a replica failed a request it had "
            "not streamed from yet", labels=("reason",))
        self._m_replicas = self.registry.gauge(
            "dl4j_fleet_router_replicas",
            "Routing-table population by liveness", labels=("state",))
        self._m_affinity_pages = self.registry.counter(
            "dl4j_fleet_router_affinity_pages_total",
            "Prefix pages predicted resident on the chosen replica at "
            "placement time (the pages the placement saved)")

    # ---------------------------------------------------------- membership
    def attach(self, handle, replica_id: Optional[str] = None) -> str:
        """Add a replica handle to the table.  It becomes placeable once
        the aggregator reports it fresh+healthy (or immediately when the
        router runs aggregator-less, e.g. in-process unit tests)."""
        rid = str(replica_id or getattr(handle, "replica_id"))
        with self._lock:
            view = ReplicaView(rid, page_size=self.page_size,
                               shadow_max_pages=self.shadow_max_pages)
            self._replicas[rid] = _Entry(handle, view)
        self.refresh(force=True)
        return rid

    def detach(self, replica_id: str) -> None:
        with self._lock:
            self._replicas.pop(replica_id, None)
            for sid in [s for s, b in self._sessions.items()
                        if b["replica"] == replica_id]:
                self._sessions[sid]["pin_id"] = None

    def drain(self, replica_id: str, draining: bool = True) -> None:
        """Admin drain: stop NEW placements (rollout waves, ops); does
        not touch requests already on the replica."""
        with self._lock:
            e = self._replicas.get(replica_id)
            if e is None:
                raise KeyError(f"unknown replica {replica_id!r}")
            e.view.draining = bool(draining)

    def replicas(self) -> List[Dict[str, Any]]:
        self.refresh()
        with self._lock:
            return [dict(e.view.as_dict(), ok=e.ok, bad=e.bad)
                    for e in self._replicas.values()]

    def refresh(self, force: bool = False) -> None:
        """Fold the aggregator's ``workers()`` table into the routing
        views: health gate, load, free pages, cache version (which gates
        each shadow index), and death-mark clearing on epoch re-base."""
        now = time.monotonic()
        with self._lock:
            if not force and now - self._last_refresh < self.refresh_interval_s:
                return
            self._last_refresh = now
            rows = {}
            if self.aggregator is not None:
                try:
                    rows = {r["worker"]: r for r in self.aggregator.workers()}
                except Exception:
                    logger.warning("fleet router: aggregator refresh failed",
                                   exc_info=True)
                    return
            for rid, e in self._replicas.items():
                v = e.view
                if self.aggregator is None:
                    # aggregator-less (in-process tests): ask the handle
                    row = getattr(e.handle, "local_view", lambda: None)()
                else:
                    row = rows.get(rid)
                if row is None:
                    # never published (still warming) or expired outright
                    v.stale = e.joined  # unknown-yet != stale
                    v.healthy = None if not e.joined else False
                    continue
                e.joined = True
                v.stale = bool(row.get("stale"))
                v.healthy = row.get("healthy")
                sched = (row.get("state") or {}).get("scheduler") or {}
                v.slots = int(sched.get("slots") or v.slots)
                v.active = int(sched.get("active") or 0)
                v.queued = int(sched.get("queued") or 0)
                cache = sched.get("cache") or {}
                v.free_pages = int(cache.get("free_pages") or 0)
                pc = row.get("prefix_cache") or {}
                v.cache_version = pc.get("version")
                v.shadow.observe_version(v.cache_version)
                if e.dead_mark is not None:
                    epoch, seq = e.dead_mark
                    if row.get("epoch") != epoch or int(row.get("seq") or 0) > seq:
                        # fresh publisher epoch (restart) or the stream
                        # advanced past the death point: it rejoined
                        e.dead_mark = None
                        v.dead = False
            by_state = {"live": 0, "stale": 0, "unhealthy": 0,
                        "draining": 0, "dead": 0}
            for e in self._replicas.values():
                v = e.view
                if v.dead:
                    by_state["dead"] += 1
                elif v.draining:
                    by_state["draining"] += 1
                elif v.stale:
                    by_state["stale"] += 1
                elif v.healthy is False:
                    by_state["unhealthy"] += 1
                else:
                    by_state["live"] += 1
            for state, count in by_state.items():
                self._m_replicas.set(count, state=state)

    # ------------------------------------------------------------- rollout
    def set_traffic_split(self, replica_id: str, fraction: float,
                          seed: int = 0) -> None:
        """Arm the seeded canary split: ``fraction`` of placements land
        on ``replica_id`` (the fleet-rollout canary phase)."""
        if not 0.0 <= fraction <= 1.0:
            raise ValueError(f"fraction must be in [0,1], got {fraction}")
        with self._lock:
            if replica_id not in self._replicas:
                raise KeyError(f"unknown replica {replica_id!r}")
            self._split = (replica_id, float(fraction), int(seed))

    def clear_traffic_split(self) -> None:
        with self._lock:
            self._split = None

    def status_counts(self, replica_id: str) -> Dict[str, int]:
        """Per-replica terminal outcomes (ok/bad) — the fleet rollout's
        watch evidence, same judged/bad vocabulary as the PR-8 watch."""
        with self._lock:
            e = self._replicas.get(replica_id)
            if e is None:
                raise KeyError(f"unknown replica {replica_id!r}")
            return {"ok": e.ok, "bad": e.bad, "judged": e.ok + e.bad}

    def _note_outcome(self, replica_id: str, ok: bool) -> None:
        with self._lock:
            e = self._replicas.get(replica_id)
            if e is not None:
                if ok:
                    e.ok += 1
                else:
                    e.bad += 1

    # ----------------------------------------------------------- placement
    def place(self, prompt: Sequence[int], *,
              session_id: Optional[str] = None,
              exclude: Iterable[str] = (),
              trace_id: Optional[str] = None) -> Placement:
        """One placement decision + its ``fleet_route`` span.  Raises
        ``NoLiveReplicaError`` when the live set is empty."""
        t0 = time.perf_counter_ns()
        tid = trace_id or new_trace_id()
        self.refresh()
        with self._lock:
            n = self._n
            self._n += 1
            session_replica = None
            if session_id is not None:
                bound = self._sessions.get(session_id)
                if bound is not None:
                    session_replica = bound["replica"]
            rid, reason, scores = choose(
                [e.view for e in self._replicas.values()], prompt,
                seed=self.seed, n=n, session_replica=session_replica,
                split=self._split, exclude=exclude,
                overload_factor=self.overload_factor, policy=self.policy)
            if rid is None:
                raise NoLiveReplicaError(
                    f"no live replica among {sorted(self._replicas)} "
                    f"[trace {tid}]")
            if session_replica is not None and rid != session_replica:
                reason = "repin"   # pinned replica gone; survivor chosen
            e = self._replicas[rid]
            saved = e.view.shadow.matched_pages(prompt)
            e.view.shadow.insert(prompt)
            e.view.inflight += 1
        self._m_requests.inc(replica=rid, reason=reason)
        if saved:
            self._m_affinity_pages.inc(saved)
        get_tracer().record_span(
            "fleet_route", t0, time.perf_counter_ns(), trace_id=tid,
            replica=rid, reason=reason, n=n,
            candidates={r: {"affinity_pages": s["affinity_pages"],
                            "load": s["load"],
                            "free_pages": s["free_pages"]}
                        for r, s in scores.items()})
        return Placement(rid, reason, scores, tid, n)

    def _entry(self, replica_id: str) -> _Entry:
        with self._lock:
            return self._replicas[replica_id]

    def _release(self, replica_id: str) -> None:
        with self._lock:
            e = self._replicas.get(replica_id)
            if e is not None and e.view.inflight > 0:
                e.view.inflight -= 1

    def _record_failover(self, reason: str, replica_id: str,
                         exc: BaseException) -> None:
        self._m_failovers.inc(reason=reason)
        with self._lock:
            e = self._replicas.get(replica_id)
            if e is not None and reason in (REPLICA_DEAD, DRAINING):
                e.view.dead = True
                e.dead_mark = (e.view.cache_version, 0)
                # mark against the replica's LAST PUBLISHED position so a
                # later snapshot (fresh epoch after restart, or the seq
                # advancing past the death) clears it
                if self.aggregator is not None:
                    try:
                        for row in self.aggregator.workers():
                            if row["worker"] == replica_id:
                                e.dead_mark = (row.get("epoch"),
                                               int(row.get("seq") or 0))
                                break
                    except Exception:
                        pass
                else:
                    e.dead_mark = (None, 0)
        logger.warning("fleet router: failover off %s (%s): %s",
                       replica_id, reason, exc)

    # -------------------------------------------------------------- submit
    def submit(self, prompt: Sequence[int], max_new_tokens: int = 32, *,
               session_id: Optional[str] = None,
               trace_id: Optional[str] = None,
               **gen_kw) -> "FleetRequest":
        """Place and submit; returns a handle whose ``stream()`` /
        ``result()`` transparently fail over while nothing has been
        streamed yet."""
        tid = trace_id or new_trace_id()
        return FleetRequest(self, list(prompt), int(max_new_tokens),
                            gen_kw, session_id, tid)

    # ------------------------------------------------------------ sessions
    def pin_session(self, session_id: str, prompt: Sequence[int]) -> str:
        """Pin a conversation: place its prefix, ``pin_prefix`` it on the
        chosen replica, and bind the session so later ``submit``s with
        this ``session_id`` stick there.  Returns the replica id."""
        placement = self.place(prompt, session_id=session_id)
        rid = placement.replica_id
        self._release(rid)   # pin itself is not an in-flight request
        pin_id = None
        try:
            pin_id = self._entry(rid).handle.pin_prefix(list(prompt))
        except Exception:
            logger.warning("fleet router: pin_prefix failed on %s "
                           "(session sticks unpinned)", rid, exc_info=True)
        with self._lock:
            self._sessions[session_id] = {
                "replica": rid, "pin_id": pin_id,
                "prompt": tuple(int(t) for t in prompt)}
        return rid

    def release_session(self, session_id: str) -> None:
        with self._lock:
            bound = self._sessions.pop(session_id, None)
        if bound and bound["pin_id"] is not None:
            try:
                self._entry(bound["replica"]).handle.unpin_prefix(
                    bound["pin_id"])
            except Exception:
                pass

    def session_replica(self, session_id: str) -> Optional[str]:
        with self._lock:
            bound = self._sessions.get(session_id)
            return bound["replica"] if bound else None

    def _rebind_session(self, session_id: str, replica_id: str) -> None:
        """Re-pin a session on the survivor after its replica died: bind
        immediately (stickiness must not lapse), re-pin best-effort (the
        prefix pages re-enter the survivor's tree on first decode)."""
        with self._lock:
            bound = self._sessions.get(session_id)
            if bound is None or bound["replica"] == replica_id:
                return
            prompt = bound["prompt"]
            bound.update(replica=replica_id, pin_id=None)
        try:
            pin_id = self._entry(replica_id).handle.pin_prefix(list(prompt))
            with self._lock:
                bound = self._sessions.get(session_id)
                if bound is not None and bound["replica"] == replica_id:
                    bound["pin_id"] = pin_id
        except Exception:
            logger.warning("fleet router: re-pin failed on %s", replica_id,
                           exc_info=True)


class FleetRequest:
    """One routed request.  Failover contract: a replica failure BEFORE
    the first streamed token is retried on a survivor (RetryPolicy
    backoff, failover metrics, session re-bind); a failure AFTER tokens
    flowed is terminal — replaying would duplicate client-visible
    output.  Queue-full rejections try another replica without marking
    the busy one dead."""

    def __init__(self, router: FleetRouter, prompt: List[int],
                 max_new_tokens: int, gen_kw: Dict[str, Any],
                 session_id: Optional[str], trace_id: str):
        self.router = router
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.gen_kw = gen_kw
        self.session_id = session_id
        self.trace_id = trace_id
        self.tokens: List[int] = []
        self.failovers = 0
        self.placements: List[Placement] = []
        self.replica_id: Optional[str] = None
        self.finish_reason: Optional[str] = None
        self.error: Optional[BaseException] = None
        self._handle = None
        self._exclude: set = set()
        self._done = False
        self._submit()

    # one placement + submit attempt, driven by RetryPolicy.run so
    # backoff, retry metrics, and flight events all come from PR 5
    def _submit(self) -> None:
        def attempt():
            placement = self.router.place(
                self.prompt, session_id=self.session_id,
                exclude=self._exclude, trace_id=self.trace_id)
            rid = placement.replica_id
            try:
                handle = self.router._entry(rid).handle.submit(
                    self.prompt, self.max_new_tokens,
                    trace_id=self.trace_id, **self.gen_kw)
            except BaseException as exc:
                self.router._release(rid)
                reason = _failover_reason(exc)
                if reason is not None:
                    self.failovers += 1
                    self.router._record_failover(reason, rid, exc)
                    self._exclude.add(rid)
                raise
            return placement, handle

        placement, handle = self.router.retry_policy.run(
            attempt, description="fleet submit",
            context={"trace_id": self.trace_id})
        self.placements.append(placement)
        self.replica_id = placement.replica_id
        self._handle = handle
        if self.session_id is not None:
            self.router._rebind_session(self.session_id, self.replica_id)

    def cancel(self) -> None:
        if self._handle is not None:
            try:
                self._handle.cancel()
            except Exception:
                pass

    def stream(self, timeout: Optional[float] = None):
        """Yield token ids; fails over while the stream is untouched."""
        while True:
            rid, h = self.replica_id, self._handle
            try:
                for tok in h.stream(timeout=timeout):
                    self.tokens.append(int(tok))
                    yield int(tok)
                self.finish_reason = (getattr(h, "finish_reason", None)
                                      or "length")
                self._finish(rid, ok=self.finish_reason in ("length", "stop"))
                return
            except GeneratorExit:
                # consumer abandoned the stream — not the replica's fault
                self.cancel()
                self.router._release(rid)
                self._done = True
                raise
            except BaseException as exc:
                reason = _failover_reason(exc)
                if (self.tokens or reason is None
                        or self.failovers >= self.router.retry_policy.max_retries):
                    self._finish(rid, ok=False, error=exc)
                    raise
                self.failovers += 1
                self.router._release(rid)
                self.router._record_failover(reason, rid, exc)
                self._exclude.add(rid)
                # seeded backoff before re-placing on a survivor
                time.sleep(self.router.retry_policy.delay(self.failovers - 1))
                self._submit()

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """Consume the stream to completion (failover included)."""
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        for _ in self.stream(timeout=timeout):
            if deadline is not None and time.monotonic() > deadline:
                self.cancel()
                raise TimeoutError(
                    f"fleet request still running [trace {self.trace_id}]")
        return list(self.tokens)

    def _finish(self, replica_id: Optional[str], ok: bool,
                error: Optional[BaseException] = None) -> None:
        if self._done:
            return
        self._done = True
        self.error = error
        if replica_id is not None:
            self.router._release(replica_id)
            self.router._note_outcome(replica_id, ok)

    def as_dict(self) -> Dict[str, Any]:
        return {"trace_id": self.trace_id, "replica": self.replica_id,
                "tokens": len(self.tokens), "failovers": self.failovers,
                "finish_reason": self.finish_reason,
                "placements": [p.as_dict() for p in self.placements]}
