"""Serving-fleet control plane: router, replicas, supervisor, rollout.

Lazy re-exports (PEP 562) so importing one corner does not pay for the
rest — ``fleet.placement`` in particular stays stdlib-only for the CI
placement-policy gate.
"""

_EXPORTS = {
    "ShadowIndex": "placement",
    "ReplicaView": "placement",
    "placement_selftest": "placement",
    "FleetRouter": "router",
    "FleetRequest": "router",
    "NoLiveReplicaError": "router",
    "InProcessReplica": "replica",
    "HTTPReplica": "replica",
    "ReplicaError": "replica",
    "ReplicaSupervisor": "supervisor",
    "ReplicaProcess": "supervisor",
    "free_port": "supervisor",
    "FleetRollout": "rollout",
    "FleetRolloutResult": "rollout",
    "FleetFrontend": "frontend",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name):
    mod = _EXPORTS.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(f"{__name__}.{mod}"), name)
