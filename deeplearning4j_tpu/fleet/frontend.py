"""HTTP edge for the fleet router: one /generate in front of N replicas.

Clients talk to this exactly like a single replica's ``InferenceServer``
(``POST /generate``, streaming or not) — the difference is what happens
behind it: the router places each request (canary split → session pin →
prefix affinity → least-loaded), mints the ``X-Request-Id`` when the
client sent none, propagates it to the replica, and fails queued
requests over to survivors.  The envelope and every SSE terminal event
carry the replica that actually served the tokens plus the failover
count; the access-log line (same ``deeplearning4j_tpu.serving.access``
logger, emitted BEFORE the response flushes) adds the placement reason.

Failover contract at this edge: a replica death before the first token
is invisible to the client (retried via the router); a death mid-stream
is a clean terminal ``data: {"error": ..., "done": true}`` event — never
a silently truncated stream.
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from deeplearning4j_tpu.observability.tracing import new_trace_id
from deeplearning4j_tpu.serving.admission import ServingError

logger = logging.getLogger("dl4j_tpu.fleet")
access_logger = logging.getLogger("deeplearning4j_tpu.serving.access")


class FleetFrontend:
    """See module docstring."""

    def __init__(self, router, port: int = 0, access_log: bool = False):
        self.router = router
        self.access_log = bool(access_log)
        self._requested_port = port
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self.port: Optional[int] = None

    def _access(self, freq, status: str, http_status: int,
                reason: Optional[str]) -> None:
        if not self.access_log:
            return
        try:
            access_logger.info(json.dumps({
                "trace_id": freq.trace_id if freq is not None else None,
                "endpoint": "fleet_generate",
                "replica": freq.replica_id if freq is not None else None,
                "placement_reason": reason,
                "failovers": freq.failovers if freq is not None else None,
                "status": status,
                "http_status": http_status,
                "tokens": len(freq.tokens) if freq is not None else None,
                "finish_reason": (freq.finish_reason
                                  if freq is not None else None),
            }))
        except Exception:
            logger.debug("fleet access-log line failed", exc_info=True)

    def start(self) -> int:
        frontend = self
        router = self.router

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _json(self, obj, code=200):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    live = [r for r in router.replicas() if r["live"]]
                    self._json({"status": "ok" if live else "unavailable",
                                "live_replicas": len(live)},
                               code=200 if live else 503)
                elif self.path == "/fleet":
                    self._json({"replicas": router.replicas()})
                else:
                    self.send_error(404)

            def do_POST(self):
                if self.path != "/generate":
                    self.send_error(404)
                    return
                # minted at the router edge when absent — the SAME id
                # rides to the replica and back (PR-7 tracing)
                tid = self.headers.get("X-Request-Id") or new_trace_id()
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    obj = json.loads(self.rfile.read(n).decode())
                    assert isinstance(obj, dict) and "prompt" in obj
                except Exception:
                    self._json({"error": 'generate body must be '
                                '{"prompt": [token ids], ...}',
                                "trace_id": tid}, code=400)
                    return
                stream = bool(obj.get("stream", False))
                kw = {}
                for src, dst in (("temperature", "temperature"),
                                 ("top_k", "top_k"), ("top_p", "top_p"),
                                 ("seed", "seed"),
                                 ("deadline_s", "deadline_s"),
                                 ("stop_token", "stop_token")):
                    if obj.get(src) is not None:
                        kw[dst] = obj[src]
                freq = None
                try:
                    freq = router.submit(
                        [int(t) for t in obj["prompt"]],
                        int(obj.get("max_tokens", 32)),
                        session_id=obj.get("session_id"),
                        trace_id=tid, **kw)
                except ServingError as e:
                    frontend._access(freq, type(e).__name__,
                                     e.http_status, None)
                    self._json({"error": str(e), "type": type(e).__name__,
                                "trace_id": tid}, code=e.http_status)
                    return
                except (TypeError, ValueError) as e:
                    self._json({"error": str(e), "type": type(e).__name__,
                                "trace_id": tid}, code=400)
                    return
                reason = (freq.placements[-1].reason
                          if freq.placements else None)
                if stream:
                    self._stream(freq, tid, reason)
                else:
                    self._unary(freq, tid, reason)

            def _unary(self, freq, tid, reason):
                try:
                    tokens = freq.result()
                except ServingError as e:
                    frontend._access(freq, type(e).__name__,
                                     e.http_status, reason)
                    self._json({"error": str(e), "type": type(e).__name__,
                                "trace_id": tid,
                                "replica": freq.replica_id},
                               code=e.http_status)
                    return
                except Exception as e:
                    frontend._access(freq, type(e).__name__, 502, reason)
                    self._json({"error": str(e), "type": type(e).__name__,
                                "trace_id": tid,
                                "replica": freq.replica_id}, code=502)
                    return
                frontend._access(freq, "ok", 200, reason)
                self._json({"tokens": tokens,
                            "finish_reason": freq.finish_reason,
                            "trace_id": tid, "replica": freq.replica_id,
                            "failovers": freq.failovers,
                            "placement_reason": reason})

            def _stream(self, freq, tid, reason):
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Cache-Control", "no-store")
                self.send_header("Connection", "close")
                self.end_headers()

                def event(payload):
                    self.wfile.write(
                        f"data: {json.dumps(payload)}\n\n".encode())
                    self.wfile.flush()

                status, code = "ok", 200
                try:
                    for i, tok in enumerate(freq.stream()):
                        event({"token": tok, "index": i, "trace_id": tid})
                    event({"done": True, "tokens": len(freq.tokens),
                           "finish_reason": freq.finish_reason,
                           "trace_id": tid, "replica": freq.replica_id,
                           "failovers": freq.failovers})
                except BrokenPipeError:
                    freq.cancel()
                    status, code = "client_disconnected", 499
                except Exception as e:
                    # mid-stream replica death (or any terminal error):
                    # the client gets a CLEAN terminal event, not EOF
                    status = type(e).__name__
                    code = getattr(e, "http_status", 502)
                    try:
                        event({"error": str(e), "type": status,
                               "trace_id": tid, "done": True,
                               "replica": freq.replica_id,
                               "failovers": freq.failovers})
                    except Exception:
                        pass
                frontend._access(freq, status, code, reason)

        self._httpd = ThreadingHTTPServer(
            ("127.0.0.1", self._requested_port), Handler)
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="fleet-frontend", daemon=True)
        self._thread.start()
        self.port = self._httpd.server_address[1]
        return self.port

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
