"""Subprocess replica entry point: ``python -m deeplearning4j_tpu.fleet.replica_main``.

One fleet replica = one process = one "device": a deterministic
``transformer_char_lm`` (same args + seed across the fleet → identical
weights, so any replica can serve any request) behind a prefix-cached
``GenerationEngine``, HTTP-fronted by ``InferenceServer`` (which gets
the ``replica_id`` it echoes in every envelope and access line), with a
``fleet_publisher`` streaming snapshots to the fleet broker — the
liveness/health/load/cache-version feed the router's membership is
gated on.  Spawned and restarted by ``fleet.supervisor``; a restart is
a fresh process and therefore a fresh publisher epoch, which the PR-18
aggregator re-bases exactly and the router reads as a rejoin.
"""

from __future__ import annotations

import argparse
import signal
import sys
import threading


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="deeplearning4j_tpu fleet replica")
    ap.add_argument("--worker-id", required=True)
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--broker-url", default=None,
                    help="fleet pubsub broker base url (no publishing "
                    "when omitted)")
    ap.add_argument("--topic", default="fleet.telemetry")
    ap.add_argument("--interval-s", type=float, default=0.5)
    ap.add_argument("--vocab", type=int, default=77)
    ap.add_argument("--d-model", type=int, default=64)
    ap.add_argument("--n-heads", type=int, default=4)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--model-seed", type=int, default=12345)
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--page-size", type=int, default=8)
    ap.add_argument("--max-context", type=int, default=96)
    ap.add_argument("--max-queue", type=int, default=64)
    ap.add_argument("--deadline-s", type=float, default=60.0)
    ap.add_argument("--prefill-buckets", default="16",
                    help="comma-separated prompt buckets")
    ap.add_argument("--step-floor-ms", type=float, default=0.0,
                    help="decode_step_floor_s pacing in ms (device-sim; "
                    "0 = off)")
    args = ap.parse_args(argv)

    # imports AFTER argparse: --help must not pay the jax tax
    from deeplearning4j_tpu.generation.engine import GenerationEngine
    from deeplearning4j_tpu.models.sequential import MultiLayerNetwork
    from deeplearning4j_tpu.models.zoo import transformer_char_lm
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.streaming.serving import InferenceServer

    lm = transformer_char_lm(
        vocab_size=args.vocab, d_model=args.d_model, n_heads=args.n_heads,
        layers=args.layers, max_cache=args.max_context,
        seed=args.model_seed)
    buckets = tuple(int(b) for b in args.prefill_buckets.split(","))
    engine = GenerationEngine(
        lm, slots=args.slots, page_size=args.page_size,
        max_context=args.max_context, max_queue=args.max_queue,
        deadline_s=args.deadline_s, prefill_buckets=buckets,
        prefix_cache=True,
        decode_step_floor_s=args.step_floor_ms / 1e3).start()

    # the server needs a predict net too; a 2-layer MLP keeps /predict
    # alive without costing warmup time
    conf = (NeuralNetConfiguration.builder().seed(1)
            .updater("sgd", learning_rate=0.1).list()
            .layer(DenseLayer(n_in=4, n_out=8, activation="relu"))
            .layer(OutputLayer(n_in=8, n_out=3, loss="mcxent",
                               activation="softmax")).build())
    pred = MultiLayerNetwork(conf).init()
    srv = InferenceServer(pred, generation=engine, access_log=True,
                          port=args.port, replica_id=args.worker_id)
    port = srv.start()

    pub = None
    if args.broker_url:
        # the serving health rules read the predict engine as extra=
        # (exactly what GET /health passes); the publisher calls bare
        # evaluate(), so bind the extra here
        class _Health:
            def evaluate(self):
                return srv.health.evaluate(extra=srv.engine)

        pub = engine.fleet_publisher(
            args.worker_id, url=args.broker_url, topic=args.topic,
            interval_s=args.interval_s, health=_Health())
        pub.start()

    stop = threading.Event()

    def _term(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    # readiness marker AFTER engine warmup + server bind: the supervisor
    # treats a 200 /healthz as the warmup barrier, this line is for logs
    print(f"replica {args.worker_id} serving on :{port}", flush=True)
    stop.wait()
    if pub is not None:
        pub.stop()
    srv.stop()
    engine.stop(drain=False)
    return 0


if __name__ == "__main__":
    sys.exit(main())
