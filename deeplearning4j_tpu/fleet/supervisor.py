"""ReplicaSupervisor: spawn / monitor / restart subprocess replicas.

The process-lifecycle quarter of the fleet control plane: port
assignment (bind-probe for a free port), warmup barrier (a replica
joins the fleet only after its ``/healthz`` answers 200, which in
``replica_main`` happens strictly after the engine AOT-warmed every
bucket — a cold replica must never take traffic), and crash → restart
→ rejoin (a restarted replica is a new process, hence a fresh
publisher epoch that the PR-18 aggregator re-bases and the router's
death-mark logic reads as a rejoin).  Restarts are capped per replica;
a replica that keeps dying stays down and stays drained.

Stdlib-only on purpose (subprocess/socket/threading + the metrics
registry): the supervisor must keep working while the thing it
supervises is the part that is broken.
"""

from __future__ import annotations

import logging
import os
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
import urllib.request
from typing import Any, Dict, List, Optional

from deeplearning4j_tpu.fleet.replica import HTTPReplica
from deeplearning4j_tpu.observability.metrics import get_registry

logger = logging.getLogger("dl4j_tpu.fleet")

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class ReplicaProcess:
    """Bookkeeping for one supervised replica."""

    __slots__ = ("worker_id", "port", "proc", "args", "restarts",
                 "restartable", "log_path")

    def __init__(self, worker_id: str, port: int, proc, args: List[str],
                 log_path: str):
        self.worker_id = worker_id
        self.port = port
        self.proc = proc
        self.args = args
        self.restarts = 0
        self.restartable = True
        self.log_path = log_path

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def log_tail(self, n: int = 30) -> str:
        try:
            with open(self.log_path, "r", errors="replace") as f:
                return "".join(f.readlines()[-n:])
        except OSError:
            return "<no log>"


class ReplicaSupervisor:
    """See module docstring."""

    def __init__(self, *, broker_url: Optional[str] = None,
                 topic: str = "fleet.telemetry",
                 python: str = sys.executable,
                 warmup_timeout_s: float = 120.0,
                 restart: bool = True, max_restarts: int = 2,
                 poll_interval_s: float = 0.25,
                 registry=None, log_dir: Optional[str] = None,
                 replica_args: Optional[Dict[str, Any]] = None):
        self.broker_url = broker_url
        self.topic = topic
        self.python = python
        self.warmup_timeout_s = float(warmup_timeout_s)
        self.restart = bool(restart)
        self.max_restarts = int(max_restarts)
        self.poll_interval_s = float(poll_interval_s)
        self.log_dir = log_dir or tempfile.mkdtemp(prefix="dl4j_fleet_")
        # per-fleet replica_main defaults (slots, step-floor-ms, ...)
        self.replica_args = dict(replica_args or {})
        self.registry = registry or get_registry()
        self._m_restarts = self.registry.counter(
            "dl4j_fleet_supervisor_restarts_total",
            "Replica processes restarted after a crash",
            labels=("worker",))
        self._procs: Dict[str, ReplicaProcess] = {}
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._monitor: Optional[threading.Thread] = None
        self.on_restart = None     # hook(worker_id, ReplicaProcess)

    # ------------------------------------------------------------- spawning
    def _cmd(self, worker_id: str, port: int,
             overrides: Dict[str, Any]) -> List[str]:
        merged = dict(self.replica_args)
        merged.update(overrides)
        cmd = [self.python, "-m", "deeplearning4j_tpu.fleet.replica_main",
               "--worker-id", worker_id, "--port", str(port)]
        if self.broker_url:
            cmd += ["--broker-url", self.broker_url, "--topic", self.topic]
        for k, v in sorted(merged.items()):
            cmd += [f"--{k.replace('_', '-')}", str(v)]
        return cmd

    def _spawn(self, worker_id: str, port: int,
               args: List[str]) -> ReplicaProcess:
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        # the replica imports the package by name: make sure the repo
        # root wins however the parent was launched
        env["PYTHONPATH"] = _REPO_ROOT + os.pathsep + env.get(
            "PYTHONPATH", "")
        log_path = os.path.join(self.log_dir, f"{worker_id}.log")
        log_f = open(log_path, "ab")
        try:
            proc = subprocess.Popen(args, stdout=log_f, stderr=log_f,
                                    env=env, cwd=_REPO_ROOT)
        finally:
            log_f.close()   # the child holds its own fd now
        return ReplicaProcess(worker_id, port, proc, args, log_path)

    def _wait_ready(self, rp: ReplicaProcess) -> None:
        """Warmup barrier: block until /healthz answers 200 (the engine
        AOT-warmed first — see replica_main) or the process dies."""
        deadline = time.monotonic() + self.warmup_timeout_s
        while time.monotonic() < deadline:
            if not rp.alive():
                raise RuntimeError(
                    f"replica {rp.worker_id} died during warmup "
                    f"(rc={rp.proc.returncode}):\n{rp.log_tail()}")
            try:
                with urllib.request.urlopen(f"{rp.url}/healthz",
                                            timeout=2.0) as resp:
                    if resp.status == 200:
                        return
            except OSError:
                pass
            time.sleep(0.1)
        raise TimeoutError(
            f"replica {rp.worker_id} not ready after "
            f"{self.warmup_timeout_s}s:\n{rp.log_tail()}")

    def start_replica(self, worker_id: str, port: Optional[int] = None,
                      wait_ready: bool = True,
                      **overrides) -> ReplicaProcess:
        with self._lock:
            if worker_id in self._procs and self._procs[worker_id].alive():
                raise RuntimeError(f"replica {worker_id} already running")
            port = port or free_port()
            rp = self._spawn(worker_id, port,
                             self._cmd(worker_id, port, overrides))
            self._procs[worker_id] = rp
        if wait_ready:
            try:
                self._wait_ready(rp)
            except Exception:
                self.stop_replica(worker_id)
                raise
        return rp

    def handle(self, worker_id: str, timeout: float = 60.0) -> HTTPReplica:
        with self._lock:
            rp = self._procs[worker_id]
        return HTTPReplica(worker_id, rp.url, timeout=timeout)

    def handles(self, timeout: float = 60.0) -> Dict[str, HTTPReplica]:
        with self._lock:
            ids = list(self._procs)
        return {wid: self.handle(wid, timeout=timeout) for wid in ids}

    def processes(self) -> Dict[str, ReplicaProcess]:
        with self._lock:
            return dict(self._procs)

    # ----------------------------------------------------------- monitoring
    def start(self) -> "ReplicaSupervisor":
        """Start the crash monitor (restart-on-death loop)."""
        if self._monitor is not None and self._monitor.is_alive():
            return self
        self._stop.clear()
        self._monitor = threading.Thread(target=self._run,
                                         name="fleet-supervisor",
                                         daemon=True)
        self._monitor.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.poll_interval_s):
            with self._lock:
                dead = [rp for rp in self._procs.values()
                        if not rp.alive() and rp.restartable]
            for rp in dead:
                if self._stop.is_set():
                    return
                self._restart(rp)

    def _restart(self, rp: ReplicaProcess) -> None:
        if not self.restart or rp.restarts >= self.max_restarts:
            if rp.restartable:
                rp.restartable = False
                logger.warning(
                    "fleet supervisor: replica %s down for good "
                    "(rc=%s, restarts=%d)", rp.worker_id,
                    rp.proc.returncode, rp.restarts)
            return
        logger.warning("fleet supervisor: restarting replica %s "
                       "(rc=%s)", rp.worker_id, rp.proc.returncode)
        new = self._spawn(rp.worker_id, rp.port, rp.args)
        new.restarts = rp.restarts + 1
        with self._lock:
            self._procs[rp.worker_id] = new
        self._m_restarts.inc(worker=rp.worker_id)
        try:
            self._wait_ready(new)
        except Exception:
            logger.warning("fleet supervisor: replica %s failed warmup "
                           "after restart", rp.worker_id, exc_info=True)
            return
        hook = self.on_restart
        if hook is not None:
            try:
                hook(rp.worker_id, new)
            except Exception:
                logger.warning("fleet supervisor: on_restart hook failed",
                               exc_info=True)

    # ------------------------------------------------------------ lifecycle
    def kill(self, worker_id: str, sig: int = signal.SIGKILL,
             restart: Optional[bool] = None) -> None:
        """Send ``sig`` to a replica (the failover drill's hammer).
        ``restart=False`` pins it down; default keeps the monitor's
        restart policy."""
        with self._lock:
            rp = self._procs[worker_id]
            if restart is not None:
                rp.restartable = bool(restart)
        if rp.alive():
            rp.proc.send_signal(sig)

    def stop_replica(self, worker_id: str, timeout: float = 10.0) -> None:
        with self._lock:
            rp = self._procs.get(worker_id)
            if rp is None:
                return
            rp.restartable = False
        if rp.alive():
            rp.proc.terminate()
            try:
                rp.proc.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                rp.proc.kill()
                rp.proc.wait(timeout=timeout)

    def stop_all(self, timeout: float = 10.0) -> None:
        self._stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=timeout)
            self._monitor = None
        with self._lock:
            ids = list(self._procs)
        for wid in ids:
            self.stop_replica(wid, timeout=timeout)
