"""Placement policy core for the serving-fleet router: pure scoring math.

STDLIB ONLY on purpose — no package imports at all.  The router's
placement decision must be simulatable without jax, numpy, or even the
rest of this package: ``scripts/ci_checks.py`` gate 6 loads THIS FILE by
path (the same pattern ``check_bench_regression.py`` uses for
``observability/regression.py``) and runs ``placement_selftest()`` as a
millisecond-fast pre-test gate.  ``fleet/router.py`` builds the live
router (handles, retries, metrics, spans) on top of these primitives.

The policy, in order:

1. **canary split** — when a traffic split is armed (fleet rollout's
   canary phase), a seeded per-request coin sends that fraction of
   placements to the canary replica.  Seeded means deterministic: the
   same seed and request sequence reproduce the same split, exactly like
   ``ServingEngine.start_canary``'s seeded router.
2. **sticky session** — a session pinned to a live replica keeps landing
   there (its prefix pages are pinned in that replica's radix tree);
   a pin to a drained/dead replica falls through to scoring so the
   caller can re-pin on the survivor.
3. **prefix-cache affinity** — each replica is scored by the longest
   expected radix-tree prefix match, in PAGES, exactly how PR 17's
   admission prices a hit: a prompt whose first ``shared_len`` tokens
   are already resident costs ``ceil((len - shared)/page)`` instead of
   ``ceil(len/page)``, so the score IS the pages saved
   (``shared_len // page_size``).  The router cannot see the remote
   radix tree itself, so it keeps a **shadow index** per replica — the
   page-aligned chunk paths of every prompt it placed there — validated
   against the replica's PUBLISHED tree version tag: a hot-swap or
   restart bumps the version and the shadow resets to zero, never
   predicting hits against an invalidated tree.  An overloaded replica
   (active + queued ≥ ``overload_factor`` × slots) forfeits its
   affinity score: a cache hit is not worth an unbounded queue.
4. **least-loaded fallback / tiebreak** — lowest ``active + queued``,
   then most free pages, then a SEEDED tie rank (stable across
   processes: ``random.Random(str)`` hashes the string arithmetically,
   not via PYTHONHASHSEED), so placement under ties is deterministic
   for a given seed and request index.
"""

from __future__ import annotations

import random
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

# placement reasons, in decision order
CANARY = "canary"
PINNED = "pinned"
AFFINITY = "affinity"
LEAST_LOADED = "least_loaded"

DEFAULT_OVERLOAD_FACTOR = 2.0


def tie_rank(seed: int, n: int, replica_id: str) -> float:
    """Deterministic per-(request, replica) tie rank in [0, 1): stable
    across processes and dict orderings (str seeding is arithmetic)."""
    return random.Random(f"{seed}:{n}:{replica_id}").random()


def canary_coin(seed: int, n: int) -> float:
    """The seeded traffic-split coin for request index ``n``."""
    return random.Random(f"canary:{seed}:{n}").random()


class ShadowIndex:
    """Router-side approximation of one replica's radix tree.

    Children keyed by exact ``page_size``-token chunk tuples — the same
    chain-identity rule as ``generation/prefix_cache.py`` (no hashing,
    no partial-chunk nodes).  Inserts record where the router SENT
    prompts; ``matched_pages`` predicts what a resubmitted prefix would
    find resident.  It is a hint, not a ledger: when the replica's
    published tree version moves (hot-swap, rollback, restart, pool
    reset) the whole shadow drops, and when the node budget fills the
    shadow clears rather than evicting piecemeal — a cold mis-predict
    costs one suboptimal placement, never a wrong answer.
    """

    __slots__ = ("page_size", "max_pages", "version", "_root", "pages")

    def __init__(self, page_size: int, max_pages: int = 8192):
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.page_size = int(page_size)
        self.max_pages = int(max_pages)
        self.version: Optional[str] = None
        self._root: Dict[Tuple[int, ...], dict] = {}
        self.pages = 0

    def observe_version(self, version: Optional[str]) -> bool:
        """Sync with the replica's published tree version; returns True
        when the shadow was reset (version moved)."""
        if version == self.version:
            return False
        self.version = version
        self.clear()
        return True

    def clear(self) -> None:
        self._root = {}
        self.pages = 0

    def _chunks(self, tokens: Sequence[int]) -> List[Tuple[int, ...]]:
        p = self.page_size
        whole = (len(tokens) // p) * p
        return [tuple(int(t) for t in tokens[i:i + p])
                for i in range(0, whole, p)]

    def insert(self, tokens: Sequence[int]) -> int:
        """Record a placed prompt; returns the number of NEW pages."""
        node, added = self._root, 0
        for chunk in self._chunks(tokens):
            child = node.get(chunk)
            if child is None:
                if self.pages >= self.max_pages:
                    # budget full: restart the hint rather than evict —
                    # see class docstring
                    self.clear()
                    node = self._root
                child = node[chunk] = {}
                self.pages += 1
                added += 1
            node = child
        return added

    def matched_pages(self, tokens: Sequence[int]) -> int:
        """Longest recorded prefix of ``tokens``, in whole pages."""
        node, n = self._root, 0
        for chunk in self._chunks(tokens):
            node = node.get(chunk)
            if node is None:
                break
            n += 1
        return n


class ReplicaView:
    """One routing-table row: everything placement needs to know about a
    replica, refreshed from the fleet aggregator's ``workers()`` table
    (load + cache version + health) and the router's own observations
    (attached handle, admin drain, observed death, local in-flight)."""

    __slots__ = ("replica_id", "healthy", "stale", "draining", "dead",
                 "slots", "active", "queued", "free_pages",
                 "cache_version", "shadow", "inflight")

    def __init__(self, replica_id: str, *, page_size: int = 16,
                 slots: int = 8, shadow_max_pages: int = 8192):
        self.replica_id = str(replica_id)
        self.healthy: Optional[bool] = None   # None = not reported
        self.stale = False
        self.draining = False                 # admin drain (rollout, ops)
        self.dead = False                     # router-observed transport death
        self.slots = int(slots)
        self.active = 0
        self.queued = 0
        self.free_pages = 0
        self.cache_version: Optional[str] = None
        self.shadow = ShadowIndex(page_size, max_pages=shadow_max_pages)
        self.inflight = 0                     # router-local, between snapshots

    @property
    def live(self) -> bool:
        return (not self.stale and not self.draining and not self.dead
                and self.healthy is not False)

    @property
    def load(self) -> int:
        """Active + queued work.  The published snapshot lags by the
        publish interval, so the router's own in-flight count floors it
        — a burst between snapshots must not pile onto one replica."""
        return max(self.active + self.queued, self.inflight)

    def as_dict(self) -> Dict[str, Any]:
        return {"replica": self.replica_id, "live": self.live,
                "healthy": self.healthy, "stale": self.stale,
                "draining": self.draining, "dead": self.dead,
                "slots": self.slots, "active": self.active,
                "queued": self.queued, "inflight": self.inflight,
                "free_pages": self.free_pages,
                "cache_version": self.cache_version,
                "shadow_pages": self.shadow.pages}


def live_views(views: Iterable[ReplicaView],
               exclude: Iterable[str] = ()) -> List[ReplicaView]:
    ex = set(exclude)
    return [v for v in views if v.live and v.replica_id not in ex]


def score(view: ReplicaView, prompt: Sequence[int], *,
          overload_factor: float = DEFAULT_OVERLOAD_FACTOR
          ) -> Dict[str, Any]:
    """One replica's placement score for one prompt (pages saved +
    load), with the overload forfeit applied (module docstring §3)."""
    pages = view.shadow.matched_pages(prompt)
    overloaded = view.load >= overload_factor * max(1, view.slots)
    return {"affinity_pages": 0 if overloaded else pages,
            "raw_affinity_pages": pages, "overloaded": overloaded,
            "load": view.load, "free_pages": view.free_pages}


def choose(views: Sequence[ReplicaView], prompt: Sequence[int], *,
           seed: int = 0, n: int = 0,
           session_replica: Optional[str] = None,
           split: Optional[Tuple[str, float, int]] = None,
           exclude: Iterable[str] = (),
           overload_factor: float = DEFAULT_OVERLOAD_FACTOR,
           policy: str = "affinity",
           ) -> Tuple[Optional[str], str, Dict[str, Dict[str, Any]]]:
    """The placement decision (module docstring).  Returns
    ``(replica_id, reason, scores)``; ``replica_id`` is None when no
    live candidate remains.  ``split`` is ``(canary_id, fraction,
    split_seed)``; ``policy="random"`` is the bench's seeded-random
    control arm (still health-gated, no affinity/load scoring)."""
    cands = live_views(views, exclude)
    scores = {v.replica_id: score(v, prompt,
                                  overload_factor=overload_factor)
              for v in cands}
    if not cands:
        return None, "no_live_replica", scores
    by_id = {v.replica_id: v for v in cands}

    if split is not None:
        canary_id, fraction, split_seed = split
        if canary_id in by_id and canary_coin(split_seed, n) < fraction:
            return canary_id, CANARY, scores

    if session_replica is not None and session_replica in by_id:
        return session_replica, PINNED, scores

    if policy == "random":
        order = sorted(by_id)
        return order[int(tie_rank(seed, n, "random") * len(order))
                     % len(order)], "random", scores

    def key(v: ReplicaView):
        s = scores[v.replica_id]
        return (-s["affinity_pages"], s["load"], -s["free_pages"],
                tie_rank(seed, n, v.replica_id), v.replica_id)

    best = min(cands, key=key)
    reason = (AFFINITY if scores[best.replica_id]["affinity_pages"] > 0
              else LEAST_LOADED)
    return best.replica_id, reason, scores


# ------------------------------------------------------------- self-test
def _sim_fleet(n: int, page_size: int = 4, slots: int = 4
               ) -> List[ReplicaView]:
    out = []
    for i in range(n):
        v = ReplicaView(f"r{i}", page_size=page_size, slots=slots)
        v.healthy, v.free_pages = True, 64
        v.cache_version = "v1"
        v.shadow.observe_version("v1")
        out.append(v)
    return out


def _sim_workload(rng: random.Random, sessions: int, requests: int,
                  page_size: int) -> List[List[int]]:
    """Session-heavy prompts: each session reuses a long shared prefix
    (the multi-turn shape the prefix cache exists for)."""
    prefixes = [[rng.randrange(200) for _ in range(4 * page_size)]
                for _ in range(sessions)]
    return [prefixes[rng.randrange(sessions)]
            + [rng.randrange(200) for _ in range(page_size)]
            for _ in range(requests)]


def _sim_run(policy: str, seed: int, page_size: int = 4
             ) -> Tuple[List[str], float]:
    """Route a seeded session workload over a 4-replica fleet whose
    per-replica caches are modeled by the shadow indexes themselves
    (insert-on-place ≙ the replica retaining the prompt's pages);
    returns (placements, fleet hit rate in pages)."""
    views = _sim_fleet(4, page_size=page_size)
    rng = random.Random(1234)
    prompts = _sim_workload(rng, sessions=6, requests=120, page_size=page_size)
    chosen_seq: List[str] = []
    hit_pages = total_pages = 0
    for n, prompt in enumerate(prompts):
        rid, _, scores = choose(views, prompt, seed=seed, n=n, policy=policy)
        assert rid is not None
        v = next(x for x in views if x.replica_id == rid)
        hit_pages += v.shadow.matched_pages(prompt)
        total_pages += len(prompt) // page_size
        v.shadow.insert(prompt)
        chosen_seq.append(rid)
    return chosen_seq, hit_pages / max(1, total_pages)


def placement_selftest(verbose: bool = False) -> int:
    """CI gate 6: the placement policy's behavioral contract, simulated
    with zero processes and zero jax.  Returns 0 on pass, 1 on fail."""
    failures: List[str] = []

    def check(name: str, ok: bool, detail: str = "") -> None:
        if verbose or not ok:
            print(f"placement_selftest: {'ok  ' if ok else 'FAIL'} {name}"
                  + (f" ({detail})" if detail else ""))
        if not ok:
            failures.append(name)

    page = 4
    # 1. deterministic under seeded ties: identical empty fleets, twice
    a, _ = _sim_run("affinity", seed=7, page_size=page)
    b, _ = _sim_run("affinity", seed=7, page_size=page)
    check("deterministic_same_seed", a == b)
    c, _ = _sim_run("affinity", seed=8, page_size=page)
    check("seed_changes_tiebreaks", a != c,
          "different seeds must break fresh-fleet ties differently")

    # 2. affinity: a session keeps landing on the replica holding it,
    #    and the fleet hit rate beats seeded-random placement
    _, hit_aff = _sim_run("affinity", seed=7, page_size=page)
    _, hit_rand = _sim_run("random", seed=7, page_size=page)
    check("affinity_beats_random", hit_aff > hit_rand,
          f"affinity {hit_aff:.3f} vs random {hit_rand:.3f}")
    views = _sim_fleet(2, page_size=page)
    prompt = list(range(3 * page))
    first, _, _ = choose(views, prompt, n=0)
    next(v for v in views if v.replica_id == first).shadow.insert(prompt)
    again, reason, scores = choose(views, prompt, n=1)
    check("session_sticks_via_affinity",
          again == first and reason == AFFINITY
          and scores[first]["affinity_pages"] == 3, f"{reason} {scores}")

    # 3. version tag invalidation: a swap/restart drops the shadow
    v0 = next(v for v in views if v.replica_id == first)
    v0.shadow.observe_version("v2")
    _, reason, scores = choose(views, prompt, n=2)
    check("version_bump_resets_shadow",
          scores[first]["affinity_pages"] == 0 and reason == LEAST_LOADED,
          f"{reason} {scores}")

    # 4. membership gating: stale / unhealthy / draining / dead replicas
    #    never take placements; an empty fleet says so
    views = _sim_fleet(3, page_size=page)
    views[0].stale = True
    views[1].healthy = False
    rid, reason, _ = choose(views, prompt, n=0)
    check("drained_excluded", rid == "r2", f"{rid} ({reason})")
    views[2].dead = True
    rid, reason, _ = choose(views, prompt, n=1)
    check("empty_fleet_reported",
          rid is None and reason == "no_live_replica")
    views[2].dead, views[2].draining = False, True
    rid, _, _ = choose(views, prompt, n=2)
    check("admin_drain_excluded", rid is None)

    # 5. least-loaded fallback + overload forfeits affinity
    views = _sim_fleet(2, page_size=page)
    views[0].shadow.insert(prompt)
    views[0].active, views[0].queued = 6, 3   # 9 >= 2.0 * 4 slots
    rid, reason, scores = choose(views, prompt, n=0)
    check("overload_forfeits_affinity",
          rid == "r1" and reason == LEAST_LOADED
          and scores["r0"]["overloaded"]
          and scores["r0"]["raw_affinity_pages"] == 3,
          f"{rid} {reason} {scores}")

    # 6. seeded canary split: deterministic and near the fraction (the
    #    split share = placements WON BY THE COIN; the canary can still
    #    win ordinary least-loaded ties on top of it)
    views = _sim_fleet(4, page_size=page)
    picks = [choose(views, prompt, n=n, split=("r2", 0.25, 5))
             for n in range(400)]
    share = sum(1 for _, reason, _ in picks
                if reason == CANARY) / len(picks)
    check("canary_split_near_fraction", 0.15 < share < 0.35,
          f"share {share:.3f}")
    picks2 = [choose(views, prompt, n=n, split=("r2", 0.25, 5))
              for n in range(400)]
    check("canary_split_deterministic",
          [p[0] for p in picks] == [p[0] for p in picks2])

    # 7. sticky pin honored while live, falls through when drained
    views = _sim_fleet(3, page_size=page)
    rid, reason, _ = choose(views, prompt, n=0, session_replica="r1")
    check("pin_honored", rid == "r1" and reason == PINNED)
    views[1].dead = True
    rid, reason, _ = choose(views, prompt, n=1, session_replica="r1")
    check("pin_falls_through_on_death",
          rid in ("r0", "r2") and reason != PINNED, f"{rid} {reason}")

    if failures:
        print(f"placement_selftest: FAIL ({len(failures)}): "
              + ", ".join(failures))
        return 1
    if verbose:
        print("placement_selftest: all checks passed")
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(placement_selftest(verbose=True))
