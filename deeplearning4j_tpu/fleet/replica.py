"""Replica handles the fleet router places onto.

Two shapes, one duck type (``submit → handle.stream()/result()``,
``pin_prefix``/``unpin_prefix``, optional ``deploy``/``rollback``/
``commit_swap``):

* ``InProcessReplica`` wraps a started ``GenerationEngine`` directly —
  the unit-test and rollout-drill shape (rollouts need ``deploy``,
  which requires the model OBJECT and therefore a shared process).
* ``HTTPReplica`` fronts a subprocess replica's ``InferenceServer``
  over urllib: ``submit`` is a streaming ``POST /generate`` whose
  admission rejections come back as typed ``ServingError`` subclasses
  (the router's failover classification needs the real types, not
  strings), and whose transport deaths surface as ``ConnectionError``s
  — transient by the PR-5 classification, which is exactly what makes
  a SIGKILLed replica's queued requests retryable on a survivor.
"""

from __future__ import annotations

import json
import threading
import urllib.error
import urllib.request
import uuid
from typing import Any, Dict, List, Optional, Sequence

from deeplearning4j_tpu.serving.admission import (
    DeadlineExceededError, ModelNotFoundError, QueueFullError,
    ServingError, ShuttingDownError)

_ERROR_TYPES = {c.__name__: c for c in (
    QueueFullError, ShuttingDownError, DeadlineExceededError,
    ModelNotFoundError)}


class ReplicaError(RuntimeError):
    """A replica-side failure that is not a typed admission rejection
    (transient vs fatal falls back to message classification)."""


def _map_error(etype: Optional[str], msg: str,
               http_status: Optional[int] = None) -> BaseException:
    cls = _ERROR_TYPES.get(etype or "")
    if cls is not None:
        return cls(msg)
    if http_status == 400 or etype == "_BadRequest":
        return ValueError(msg)         # the client's fault: fatal, no retry
    return ReplicaError(f"{etype or 'error'}: {msg}")


class InProcessReplica:
    """A started ``GenerationEngine`` as a fleet replica (class doc)."""

    can_deploy = True

    def __init__(self, replica_id: str, engine):
        self.replica_id = str(replica_id)
        self.engine = engine
        self._epoch = uuid.uuid4().hex[:12]
        self._seq = 0
        self._lock = threading.Lock()

    # -------- routing-table row for aggregator-less (unit-test) routers
    def local_view(self) -> Dict[str, Any]:
        eng = self.engine
        alive = eng._thread is not None and eng._thread.is_alive()
        with self._lock:
            if alive:
                # seq advances only while the decode thread lives, so a
                # router death-mark keyed on (epoch, seq) stays put for a
                # stopped engine — same contract as a publisher going
                # silent after SIGKILL
                self._seq += 1
            seq = self._seq
        return {"worker": self.replica_id, "stale": False,
                "healthy": alive, "epoch": self._epoch, "seq": seq,
                "state": {"scheduler": eng.scheduler.as_dict()},
                "prefix_cache": (eng.prefix_cache.stats()
                                 if eng.prefix_cache is not None else None)}

    # ------------------------------------------------------------ serving
    def submit(self, prompt: Sequence[int], max_new_tokens: int = 32,
               **kw):
        return self.engine.submit(list(prompt), max_new_tokens, **kw)

    def pin_prefix(self, prompt: Sequence[int]) -> int:
        return self.engine.pin_prefix(list(prompt))

    def unpin_prefix(self, pin_id: int) -> None:
        self.engine.unpin_prefix(pin_id)

    def cache_stats(self) -> Dict[str, Any]:
        return self.engine.cache_stats()

    # ------------------------------------------------------------ rollout
    def deploy(self, name: str, model, **kw):
        return self.engine.deploy(name, model, **kw)

    def rollback(self, name: str):
        return self.engine.rollback(name)

    def commit_swap(self, name: str) -> None:
        self.engine.commit_swap(name)


class _HTTPStream:
    """One in-flight streaming ``POST /generate``: SSE parse + the
    GenerationRequest-shaped surface the router consumes."""

    def __init__(self, resp, trace_id: Optional[str]):
        self._resp = resp
        self.trace_id = trace_id
        self.tokens: List[int] = []
        self.finish_reason: Optional[str] = None
        self.ttft_ms: Optional[float] = None
        self.replica: Optional[str] = None

    def stream(self, timeout: Optional[float] = None):
        """Yield token ids; raises the mapped replica error from a
        terminal SSE error event, or ``ConnectionError`` when the
        stream dies without one (killed replica)."""
        while True:
            line = self._resp.readline()
            if not line:
                raise ConnectionError(
                    "replica stream ended without terminal event "
                    f"[trace {self.trace_id}]")
            line = line.strip()
            if not line.startswith(b"data: "):
                continue
            ev = json.loads(line[len(b"data: "):].decode())
            if "token" in ev:
                tok = int(ev["token"])
                self.tokens.append(tok)
                yield tok
            elif ev.get("error"):
                self.replica = ev.get("replica")
                raise _map_error(ev.get("type"), ev["error"])
            elif ev.get("done"):
                self.finish_reason = ev.get("finish_reason")
                self.ttft_ms = ev.get("ttft_ms")
                self.replica = ev.get("replica")
                return

    def result(self, timeout: Optional[float] = None) -> List[int]:
        for _ in self.stream(timeout=timeout):
            pass
        return list(self.tokens)

    def cancel(self) -> None:
        # closing the socket surfaces as BrokenPipeError in the replica's
        # SSE writer, which cancels the decode request server-side
        try:
            self._resp.close()
        except Exception:
            pass


class HTTPReplica:
    """A subprocess replica behind its ``InferenceServer`` (class doc)."""

    can_deploy = False   # deploy needs the model object: in-process only

    def __init__(self, replica_id: str, url: str, timeout: float = 60.0):
        self.replica_id = str(replica_id)
        self.url = url.rstrip("/")
        self.timeout = float(timeout)

    def _post(self, path: str, body: Dict[str, Any],
              headers: Optional[Dict[str, str]] = None,
              stream: bool = False):
        req = urllib.request.Request(
            f"{self.url}{path}", data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json", **(headers or {})})
        try:
            resp = urllib.request.urlopen(req, timeout=self.timeout)
        except urllib.error.HTTPError as e:
            try:
                payload = json.loads(e.read().decode())
            except Exception:
                payload = {}
            raise _map_error(payload.get("type"),
                             payload.get("error", str(e)),
                             http_status=e.code) from e
        if stream:
            return resp
        with resp:
            return json.loads(resp.read().decode())

    def _get(self, path: str) -> Dict[str, Any]:
        try:
            with urllib.request.urlopen(f"{self.url}{path}",
                                        timeout=self.timeout) as resp:
                return json.loads(resp.read().decode())
        except urllib.error.HTTPError as e:
            try:
                payload = json.loads(e.read().decode())
            except Exception:
                payload = {}
            raise _map_error(payload.get("type"),
                             payload.get("error", str(e)),
                             http_status=e.code) from e

    # ------------------------------------------------------------ serving
    def submit(self, prompt: Sequence[int], max_new_tokens: int = 32, *,
               temperature: float = 0.0, top_k=None, top_p=None,
               seed: int = 0, deadline_s=None, stop_token=None,
               trace_id: Optional[str] = None) -> _HTTPStream:
        body = {"prompt": [int(t) for t in prompt],
                "max_tokens": int(max_new_tokens),
                "temperature": temperature, "seed": seed, "stream": True}
        if top_k is not None:
            body["top_k"] = top_k
        if top_p is not None:
            body["top_p"] = top_p
        if deadline_s is not None:
            body["deadline_s"] = deadline_s
        if stop_token is not None:
            body["stop_token"] = stop_token
        headers = {"X-Request-Id": trace_id} if trace_id else None
        resp = self._post("/generate", body, headers=headers, stream=True)
        return _HTTPStream(resp, trace_id)

    def pin_prefix(self, prompt: Sequence[int]) -> int:
        return int(self._post("/generation/pin",
                              {"prompt": [int(t) for t in prompt]})["pin_id"])

    def unpin_prefix(self, pin_id: int) -> None:
        self._post("/generation/unpin", {"pin_id": int(pin_id)})

    # --------------------------------------------------------------- probes
    def healthz(self) -> bool:
        try:
            return bool(self._get("/healthz").get("dispatcher_alive"))
        except (ServingError, ValueError, ReplicaError, OSError):
            return False

    def health(self) -> Dict[str, Any]:
        # /health answers 503 WITH the verdict body when unhealthy —
        # the caller wants the verdict either way, not an exception
        try:
            with urllib.request.urlopen(f"{self.url}/health",
                                        timeout=self.timeout) as resp:
                return json.loads(resp.read().decode())
        except urllib.error.HTTPError as e:
            return json.loads(e.read().decode())

    def cache_stats(self) -> Dict[str, Any]:
        return self._get("/generation/cache")

    def metrics_text(self) -> str:
        with urllib.request.urlopen(f"{self.url}/metrics",
                                    timeout=self.timeout) as resp:
            return resp.read().decode()
