"""Fleet-wide canary rollout: the PR-8 promotion state machine at fleet scope.

Single-engine promotion (``online/promotion.py``) walks gate → canary →
watch → rollback|commit against ONE engine.  ``FleetRollout.consider``
runs the same machine across N replicas:

* **canary** — deploy the candidate on ONE replica (``retain_old=True``,
  the same two-resident-versions contract as single-engine canary) and
  have the ROUTER shift a seeded traffic fraction there
  (``set_traffic_split``) — the fleet analog of the serving engine's
  seeded per-request canary router.  Judged evidence is the canary
  replica's SLO delta from the PR-18 aggregator (finished/good since the
  split opened; sheds never count) with the router's own terminal-outcome
  tallies as the aggregator-less fallback.  Insufficient evidence inside
  the deadline → not promotable, same as PR 8's canary abstention.
* **wave** — remaining replicas one at a time, each deploy followed by a
  watch window evaluated through ``HealthEvaluator`` over the SAME
  default watch rules as single-engine promotion (error-rate delta +
  probe), fed from per-replica SLO deltas.
* **rollback** — any canary breach or watch regression rolls back EVERY
  replica deployed so far, newest first, and re-clears the traffic
  split; a replica whose rollback itself fails reports
  ``rollback_failed`` (the alarm outcome, exactly PR 8's).
* **commit** — all replicas watched clean → ``commit_swap`` everywhere.

Outcomes land in ``dl4j_fleet_rollout_total{outcome}`` and the flight
recorder; the outcome vocabulary is ``online.promotion``'s, imported
lazily so this module stays importable without the online stack.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Callable, Dict, List, Optional

from deeplearning4j_tpu.observability.health import HealthEvaluator
from deeplearning4j_tpu.observability.metrics import get_registry

logger = logging.getLogger("dl4j_tpu.fleet")

# same outcome vocabulary as online.promotion (string-equal on purpose:
# dashboards already aggregate these label values)
REJECTED = "rejected"
CANARY_REJECTED = "canary_rejected"
ROLLED_BACK = "rolled_back"
ROLLBACK_FAILED = "rollback_failed"
PROMOTED = "promoted"


def _default_watch_rules(max_error_rate: float, min_requests: int):
    # the PR-8 rule builders read plain extra dicts — reuse them verbatim
    from deeplearning4j_tpu.online.promotion import default_watch_rules
    return default_watch_rules(max_error_rate=max_error_rate,
                               min_requests=min_requests)


class FleetRolloutResult:
    """One candidate's walk across the fleet."""

    def __init__(self, candidate_id: str):
        self.candidate_id = candidate_id
        self.outcome: Optional[str] = None
        self.canary: Optional[Dict[str, Any]] = None
        self.waves: List[Dict[str, Any]] = []
        self.rolled_back: List[str] = []      # replicas restored
        self.committed: List[str] = []
        self.detail: Optional[str] = None

    @property
    def promoted(self) -> bool:
        return self.outcome == PROMOTED

    def as_dict(self) -> Dict[str, Any]:
        return {"candidate": self.candidate_id, "outcome": self.outcome,
                "canary": self.canary, "waves": self.waves,
                "rolled_back": self.rolled_back,
                "committed": self.committed, "detail": self.detail}


class FleetRollout:
    """See module docstring.  ``replicas`` maps replica id → handle;
    every handle must be deploy-capable (``can_deploy``), i.e. the
    in-process shape — subprocess replicas would need the model object
    shipped across the boundary, which ``HTTPReplica`` does not do."""

    def __init__(self, router, replicas: Dict[str, Any], *,
                 model_name: str = "default",
                 canary_fraction: float = 0.25,
                 canary_min_requests: int = 8,
                 canary_timeout_s: float = 30.0,
                 canary_max_error_rate: float = 0.05,
                 watch_rules=None,
                 watch_window_s: float = 2.0,
                 watch_poll_s: float = 0.1,
                 watch_min_requests: int = 1,
                 watch_max_error_rate: float = 0.05,
                 watch_extra_fn: Optional[Callable[[str], dict]] = None,
                 split_seed: int = 0,
                 registry=None):
        undeployable = [rid for rid, h in replicas.items()
                        if not getattr(h, "can_deploy", False)]
        if undeployable:
            raise ValueError(
                f"fleet rollout needs deploy-capable replicas; "
                f"{undeployable} are not (HTTP replicas cannot receive "
                f"a model object)")
        self.router = router
        self.replicas = dict(replicas)
        self.model_name = model_name
        self.canary_fraction = float(canary_fraction)
        self.canary_min_requests = int(canary_min_requests)
        self.canary_timeout_s = float(canary_timeout_s)
        self.canary_max_error_rate = float(canary_max_error_rate)
        self._watch_rules = watch_rules
        self.watch_window_s = float(watch_window_s)
        self.watch_poll_s = float(watch_poll_s)
        self.watch_min_requests = int(watch_min_requests)
        self.watch_max_error_rate = float(watch_max_error_rate)
        self.watch_extra_fn = watch_extra_fn
        self.split_seed = int(split_seed)
        self.registry = registry or get_registry()
        self._m_outcomes = self.registry.counter(
            "dl4j_fleet_rollout_total",
            "Fleet-wide rollout outcomes", labels=("outcome",))

    # ------------------------------------------------------------- evidence
    def _slo_counts(self, replica_id: str) -> Dict[str, int]:
        """(finished, good) for one replica: the aggregator's published
        SLO row when available, the router's terminal tallies otherwise."""
        agg = getattr(self.router, "aggregator", None)
        if agg is not None:
            try:
                for row in agg.workers():
                    if row["worker"] == replica_id and row.get("slo"):
                        slo = row["slo"]
                        return {"finished": int(slo.get("finished") or 0),
                                "good": int(slo.get("good_total") or 0)}
            except Exception:
                logger.warning("fleet rollout: aggregator evidence read "
                               "failed", exc_info=True)
        counts = self.router.status_counts(replica_id)
        return {"finished": counts["judged"], "good": counts["ok"]}

    def _watch_extra(self, replica_id: str,
                     base: Dict[str, int]) -> Dict[str, Any]:
        now = self._slo_counts(replica_id)
        requests = max(0, now["finished"] - base["finished"])
        good = max(0, now["good"] - base["good"])
        bad = max(0, requests - good)
        extra: Dict[str, Any] = {
            "replica": replica_id, "requests": requests, "bad": bad,
            "error_rate": bad / requests if requests else 0.0,
        }
        if self.watch_extra_fn is not None:
            extra.update(self.watch_extra_fn(replica_id) or {})
        return extra

    # ------------------------------------------------------------ mechanics
    def _finish(self, res: FleetRolloutResult, outcome: str,
                detail: Optional[str] = None) -> FleetRolloutResult:
        res.outcome, res.detail = outcome, detail
        self._m_outcomes.inc(outcome=outcome)
        try:
            from deeplearning4j_tpu.observability import get_flight_recorder
            get_flight_recorder().record(
                "fleet_rollout", candidate=res.candidate_id,
                outcome=outcome, detail=detail,
                rolled_back=list(res.rolled_back),
                committed=list(res.committed))
        except Exception:
            pass
        return res

    def _rollback_all(self, res: FleetRolloutResult,
                      deployed: List[str]) -> Optional[str]:
        """Newest-first fleet restore; returns the failure detail when a
        rollback itself broke (→ ROLLBACK_FAILED)."""
        failed = None
        for rid in reversed(deployed):
            try:
                self.replicas[rid].rollback(self.model_name)
                res.rolled_back.append(rid)
            except Exception as e:
                logger.error("fleet rollout: rollback FAILED on %s",
                             rid, exc_info=True)
                failed = f"rollback failed on {rid}: {e}"
        return failed

    # --------------------------------------------------------------- driver
    def consider(self, model, candidate_id: str = "candidate"
                 ) -> FleetRolloutResult:
        res = FleetRolloutResult(candidate_id)
        order = sorted(self.replicas)
        live = {r["replica"] for r in self.router.replicas() if r["live"]}
        placeable = [rid for rid in order if rid in live]
        if not placeable:
            return self._finish(res, REJECTED, "no live replica to canary")
        canary_id = placeable[0]
        deployed: List[str] = []

        # ---- canary: one replica + seeded router split
        try:
            self.replicas[canary_id].deploy(self.model_name, model,
                                            retain_old=True)
            deployed.append(canary_id)
        except Exception as e:
            return self._finish(res, REJECTED,
                                f"canary deploy broke on {canary_id}: {e}")
        base = self._slo_counts(canary_id)
        self.router.set_traffic_split(canary_id, self.canary_fraction,
                                      seed=self.split_seed)
        try:
            deadline = time.monotonic() + self.canary_timeout_s
            while True:
                extra = self._watch_extra(canary_id, base)
                if extra["requests"] >= self.canary_min_requests:
                    break
                if time.monotonic() > deadline:
                    break
                time.sleep(self.watch_poll_s)
        finally:
            self.router.clear_traffic_split()
        res.canary = dict(extra, replica=canary_id,
                          fraction=self.canary_fraction)
        if extra["requests"] < self.canary_min_requests:
            failed = self._rollback_all(res, deployed)
            return self._finish(
                res, ROLLBACK_FAILED if failed else CANARY_REJECTED,
                failed or f"insufficient canary evidence "
                f"({extra['requests']}/{self.canary_min_requests})")
        if extra["error_rate"] > self.canary_max_error_rate:
            failed = self._rollback_all(res, deployed)
            return self._finish(
                res, ROLLBACK_FAILED if failed else CANARY_REJECTED,
                failed or f"canary error rate {extra['error_rate']:.3f} > "
                f"{self.canary_max_error_rate}")

        # ---- wave: remaining replicas one at a time, watched
        rules = (self._watch_rules if self._watch_rules is not None
                 else _default_watch_rules(self.watch_max_error_rate,
                                           self.watch_min_requests))
        watcher = HealthEvaluator(rules, component="fleet_rollout",
                                  registry=self.registry)
        for rid in [r for r in order if r != canary_id]:
            try:
                self.replicas[rid].deploy(self.model_name, model,
                                          retain_old=True)
                deployed.append(rid)
            except Exception as e:
                failed = self._rollback_all(res, deployed)
                return self._finish(
                    res, ROLLBACK_FAILED if failed else ROLLED_BACK,
                    failed or f"wave deploy broke on {rid}: {e}")
            base = self._slo_counts(rid)
            verdict = None
            wave_deadline = time.monotonic() + self.watch_window_s
            while True:
                extra = self._watch_extra(rid, base)
                verdict = watcher.evaluate(extra=extra)
                if not verdict.healthy or time.monotonic() > wave_deadline:
                    break
                time.sleep(self.watch_poll_s)
            res.waves.append({"replica": rid, "extra": extra,
                              "healthy": verdict.healthy,
                              "failing": list(verdict.failing)})
            if not verdict.healthy:
                failed = self._rollback_all(res, deployed)
                return self._finish(
                    res, ROLLBACK_FAILED if failed else ROLLED_BACK,
                    failed or f"watch regression on {rid}: "
                    f"{verdict.failing}")

        # ---- commit everywhere
        for rid in deployed:
            try:
                self.replicas[rid].commit_swap(self.model_name)
                res.committed.append(rid)
            except Exception as e:
                logger.warning("fleet rollout: commit_swap failed on %s "
                               "(old version stays resident): %s", rid, e)
        return self._finish(res, PROMOTED)
