"""Regression evaluation — MSE/MAE/RMSE/RSE/R^2 per output column.

Reference: ``eval/RegressionEvaluation.java`` (streaming accumulation of
per-column stats so arbitrarily many batches fold in)."""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np


class RegressionEvaluation:
    def __init__(self, n_columns: Optional[int] = None,
                 column_names: Optional[Sequence[str]] = None):
        self.column_names = list(column_names) if column_names else None
        if n_columns is None and column_names:
            n_columns = len(column_names)
        self.n = n_columns
        self._initialized = False

    def _ensure(self, c):
        if not self._initialized:
            self.n = self.n or c
            z = lambda: np.zeros(self.n, np.float64)
            self.sum_sq_err = z()
            self.sum_abs_err = z()
            self.sum_label = z()
            self.sum_label_sq = z()
            self.sum_pred = z()
            self.sum_pred_sq = z()
            self.sum_label_pred = z()
            self.count = 0
            self._initialized = True

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels, np.float64)
        predictions = np.asarray(predictions, np.float64)
        if labels.ndim == 3:
            if mask is not None:
                m = np.asarray(mask).reshape(-1).astype(bool)
                labels = labels.reshape(-1, labels.shape[-1])[m]
                predictions = predictions.reshape(-1, predictions.shape[-1])[m]
            else:
                labels = labels.reshape(-1, labels.shape[-1])
                predictions = predictions.reshape(-1, predictions.shape[-1])
        self._ensure(labels.shape[-1])
        err = predictions - labels
        self.sum_sq_err += (err ** 2).sum(0)
        self.sum_abs_err += np.abs(err).sum(0)
        self.sum_label += labels.sum(0)
        self.sum_label_sq += (labels ** 2).sum(0)
        self.sum_pred += predictions.sum(0)
        self.sum_pred_sq += (predictions ** 2).sum(0)
        self.sum_label_pred += (labels * predictions).sum(0)
        self.count += labels.shape[0]

    def mean_squared_error(self, c: int) -> float:
        return float(self.sum_sq_err[c] / self.count)

    def mean_absolute_error(self, c: int) -> float:
        return float(self.sum_abs_err[c] / self.count)

    def root_mean_squared_error(self, c: int) -> float:
        return float(np.sqrt(self.sum_sq_err[c] / self.count))

    def relative_squared_error(self, c: int) -> float:
        mean_label = self.sum_label[c] / self.count
        tss = self.sum_label_sq[c] - self.count * mean_label ** 2
        return float(self.sum_sq_err[c] / tss) if tss else float("inf")

    def correlation_r2(self, c: int) -> float:
        n = self.count
        num = n * self.sum_label_pred[c] - self.sum_label[c] * self.sum_pred[c]
        d1 = n * self.sum_label_sq[c] - self.sum_label[c] ** 2
        d2 = n * self.sum_pred_sq[c] - self.sum_pred[c] ** 2
        den = np.sqrt(d1 * d2)
        return float(num / den) if den else 0.0

    def average_mean_squared_error(self) -> float:
        return float(np.mean(self.sum_sq_err / self.count))

    def stats(self) -> str:
        lines = ["================ RegressionEvaluation ================"]
        for c in range(self.n):
            name = self.column_names[c] if self.column_names else f"col{c}"
            lines.append(
                f" {name}: MSE={self.mean_squared_error(c):.6f} "
                f"MAE={self.mean_absolute_error(c):.6f} "
                f"RMSE={self.root_mean_squared_error(c):.6f} "
                f"R2={self.correlation_r2(c):.4f}"
            )
        return "\n".join(lines)
