"""Classification evaluation — accuracy/precision/recall/F1, top-N, confusion
matrix, time-series masking, per-example metadata attribution.

Reference: ``eval/Evaluation.java:43,160-374`` (eval, topN :290-300,
evalTimeSeries :314-346), ``eval/ConfusionMatrix.java``.  The counting is
vectorised: one on-device pass builds the [C, C] confusion matrix via a
scatter-add; derived metrics are tiny host math.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np


class ConfusionMatrix:
    def __init__(self, n_classes: int):
        self.n = n_classes
        self.matrix = np.zeros((n_classes, n_classes), np.int64)

    def add(self, actual: int, predicted: int, count: int = 1):
        self.matrix[actual, predicted] += count

    def add_matrix(self, m: np.ndarray):
        self.matrix += m.astype(np.int64)

    def count(self, actual: int, predicted: int) -> int:
        return int(self.matrix[actual, predicted])

    def actual_total(self, c: int) -> int:
        return int(self.matrix[c].sum())

    def predicted_total(self, c: int) -> int:
        return int(self.matrix[:, c].sum())

    def __str__(self):
        return str(self.matrix)


class Evaluation:
    def __init__(self, n_classes: Optional[int] = None,
                 labels: Optional[Sequence[str]] = None, top_n: int = 1):
        self.label_names = list(labels) if labels else None
        if n_classes is None and labels:
            n_classes = len(labels)
        self.n_classes = n_classes
        self.top_n = top_n
        self.confusion: Optional[ConfusionMatrix] = None
        self.top_n_correct = 0
        self.top_n_total = 0
        # per-example metadata attribution (reference eval/meta/)
        self.prediction_errors: List = []

    def _ensure(self, c: int):
        if self.confusion is None:
            self.n_classes = self.n_classes or c
            self.confusion = ConfusionMatrix(self.n_classes)

    def eval(self, labels, predictions, mask=None, metadata=None):
        """labels/predictions: [batch, C] one-hot/probabilities, or
        [batch, time, C] with optional [batch, time] mask (reference
        evalTimeSeries)."""
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        if labels.ndim == 3:
            if mask is not None:
                mask = np.asarray(mask).reshape(-1).astype(bool)
                labels = labels.reshape(-1, labels.shape[-1])[mask]
                predictions = predictions.reshape(-1, predictions.shape[-1])[mask]
            else:
                labels = labels.reshape(-1, labels.shape[-1])
                predictions = predictions.reshape(-1, predictions.shape[-1])
        C = labels.shape[-1]
        self._ensure(C)
        actual = labels.argmax(-1)
        pred = predictions.argmax(-1)
        # one-pass confusion matrix (scatter-add)
        m = np.zeros((C, C), np.int64)
        np.add.at(m, (actual, pred), 1)
        self.confusion.add_matrix(m)
        # top-N (reference :290-300)
        if self.top_n > 1:
            order = np.argsort(-predictions, axis=-1)[:, : self.top_n]
            self.top_n_correct += int((order == actual[:, None]).any(-1).sum())
        else:
            self.top_n_correct += int((pred == actual).sum())
        self.top_n_total += len(actual)
        if metadata is not None:
            for i, (a, p) in enumerate(zip(actual, pred)):
                if a != p:
                    self.prediction_errors.append((metadata[i], int(a), int(p)))

    # ---- derived metrics -------------------------------------------------
    def _tp(self, c):
        return self.confusion.count(c, c)

    def accuracy(self) -> float:
        m = self.confusion.matrix
        total = m.sum()
        return float(np.trace(m) / total) if total else 0.0

    def top_n_accuracy(self) -> float:
        return self.top_n_correct / self.top_n_total if self.top_n_total else 0.0

    def precision(self, c: Optional[int] = None) -> float:
        if c is not None:
            pt = self.confusion.predicted_total(c)
            return self._tp(c) / pt if pt else 0.0
        vals = [self.precision(i) for i in range(self.n_classes)
                if self.confusion.actual_total(i) > 0]
        return float(np.mean(vals)) if vals else 0.0

    def recall(self, c: Optional[int] = None) -> float:
        if c is not None:
            at = self.confusion.actual_total(c)
            return self._tp(c) / at if at else 0.0
        vals = [self.recall(i) for i in range(self.n_classes)
                if self.confusion.actual_total(i) > 0]
        return float(np.mean(vals)) if vals else 0.0

    def f1(self, c: Optional[int] = None) -> float:
        p, r = self.precision(c), self.recall(c)
        return 2 * p * r / (p + r) if (p + r) else 0.0

    def false_positive_rate(self, c: int) -> float:
        fp = self.confusion.predicted_total(c) - self._tp(c)
        neg = self.confusion.matrix.sum() - self.confusion.actual_total(c)
        return fp / neg if neg else 0.0

    def stats(self) -> str:
        lines = ["==================== Evaluation ===================="]
        lines.append(f" Examples:  {self.confusion.matrix.sum()}")
        lines.append(f" Accuracy:  {self.accuracy():.4f}")
        if self.top_n > 1:
            lines.append(f" Top-{self.top_n} Accuracy: {self.top_n_accuracy():.4f}")
        lines.append(f" Precision: {self.precision():.4f}")
        lines.append(f" Recall:    {self.recall():.4f}")
        lines.append(f" F1 Score:  {self.f1():.4f}")
        lines.append("Confusion matrix (rows=actual, cols=predicted):")
        lines.append(str(self.confusion))
        return "\n".join(lines)
