"""SLO-gated promotion: evaluate -> gate -> canary -> swap -> watch ->
commit | rollback.

Every candidate the online pipeline trains walks this state machine
before (and after) it touches serving:

1. **evaluate** — score the candidate (and the currently active version,
   for a relative baseline) on a held-out eval set;
2. **gate** — a ``HealthEvaluator`` over declarative ``HealthRule``
   predicates reading the eval report (absolute loss cap, accuracy
   floor, no-worse-than-active regression bound).  A failing candidate is
   recorded (``promotion_rejected`` flight event naming it +
   ``dl4j_promotions_total{outcome="rejected"}``) and never touches the
   registry;
3. **canary** — the candidate serves a seeded traffic fraction under
   ``<name>:canary`` (``ServingEngine.start_canary``) until it has seen
   ``canary_min_requests`` rerouted requests (or the phase times out);
   an error rate above ``canary_max_error_rate`` tears the canary down
   and rejects — the primary version never stopped serving;
4. **swap** — ``deploy(..., retain_old=True)``: zero-drop atomic flip
   with the previous version RETAINED as the rollback target;
5. **watch** — for ``watch_window_s`` the post-swap serving metrics are
   re-evaluated every poll (request error-rate delta since the swap,
   plus an optional self-probe through the real serving path); any
   failing watch rule triggers **automatic rollback** to the retained
   version (``dl4j_promotions_total{outcome="rolled_back"}``), otherwise
   the swap commits and the retained version retires.

The gate and the watch both reuse ``observability.health``
(``HealthEvaluator`` / ``HealthRule``), so promotion SLOs read exactly
like the /health SLOs operators already write — see docs/online.md.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Dict, List, Optional

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.observability.flightrecorder import (
    get_flight_recorder,
)
from deeplearning4j_tpu.observability.health import (
    HealthEvaluator, HealthRule,
)
from deeplearning4j_tpu.serving.admission import (
    ModelNotFoundError, ServingError,
)
from deeplearning4j_tpu.serving.engine import DEFAULT_MODEL, ServingEngine

_PROMOTIONS = "dl4j_promotions_total"
_FRESHNESS = "dl4j_online_model_freshness_seconds"

logger = logging.getLogger("deeplearning4j_tpu.online")

REJECTED = "rejected"
CANARY_REJECTED = "canary_rejected"
ROLLED_BACK = "rolled_back"
ROLLBACK_FAILED = "rollback_failed"   # watch failed, retained version gone
PROMOTED = "promoted"


def default_gate_rules(max_eval_loss: Optional[float] = None,
                       min_accuracy: Optional[float] = None,
                       max_loss_regression: Optional[float] = 0.25,
                       ) -> List[HealthRule]:
    """Gate SLOs over the candidate eval report (the ``extra`` the
    predicates receive): an absolute loss cap, an accuracy floor, and a
    relative bound — candidate loss may not exceed the ACTIVE version's
    loss by more than ``max_loss_regression`` (fractional).  Rules with
    no data to judge (no eval set, no active baseline) pass — same
    "no data is healthy" convention as ``HealthRule.require_data``."""
    rules: List[HealthRule] = []
    if max_eval_loss is not None:
        def _loss_cap(r, limit=max_eval_loss):
            loss = (r or {}).get("loss")
            if loss is None:
                return True, None, "no eval loss; pass"
            return (np.isfinite(loss) and loss <= limit, loss,
                    f"candidate eval loss vs cap {limit}")
        rules.append(HealthRule("candidate_loss_cap", "predicate",
                                fn=_loss_cap))
    if min_accuracy is not None:
        def _acc_floor(r, limit=min_accuracy):
            acc = (r or {}).get("accuracy")
            if acc is None:
                return True, None, "no eval accuracy; pass"
            return acc >= limit, acc, f"candidate accuracy vs floor {limit}"
        rules.append(HealthRule("candidate_accuracy_floor", "predicate",
                                fn=_acc_floor))
    if max_loss_regression is not None:
        def _no_regression(r, tol=max_loss_regression):
            r = r or {}
            loss, active = r.get("loss"), r.get("active_loss")
            if loss is None or active is None or not np.isfinite(active):
                return True, loss, "no active baseline; pass"
            limit = active * (1.0 + tol) if active >= 0 else \
                active * (1.0 - tol)
            return (np.isfinite(loss) and loss <= limit, loss,
                    f"candidate loss vs active {active:.6g} * (1+{tol})")
        rules.append(HealthRule("no_loss_regression_vs_active", "predicate",
                                fn=_no_regression))
    return rules


def default_watch_rules(max_error_rate: float = 0.05,
                        min_requests: int = 1) -> List[HealthRule]:
    """Post-swap SLOs over the watch window's ``extra``: the request
    error-rate delta since the swap (errors + deadline expiries over all
    requests; sheds excluded — a full queue is load, not the model) and
    the self-probe verdict.  Below ``min_requests`` the error-rate rule
    abstains — one unlucky request must not roll a good model back."""
    def _error_rate(e):
        e = e or {}
        n, rate = e.get("requests", 0), e.get("error_rate", 0.0)
        if n < min_requests:
            return (True, rate,
                    f"only {n} post-swap requests (< {min_requests}); "
                    f"insufficient evidence")
        return (rate <= max_error_rate, rate,
                f"{e.get('bad', 0)}/{n} bad post-swap requests vs limit "
                f"{max_error_rate}")

    def _probe(e):
        e = e or {}
        return (bool(e.get("probe_ok", True)), e.get("probe_ok"),
                e.get("probe_detail"))

    return [HealthRule("post_swap_error_rate", "predicate", fn=_error_rate),
            HealthRule("post_swap_probe", "predicate", fn=_probe)]


class PromotionResult:
    """One candidate's walk through the state machine."""

    def __init__(self, candidate_id: str):
        self.candidate_id = candidate_id
        self.outcome: Optional[str] = None
        self.version: Optional[int] = None      # registry version if swapped
        self.report: Dict[str, Any] = {}        # eval metrics
        self.gate: Optional[dict] = None        # gate verdict
        self.canary: Optional[dict] = None      # canary stats
        self.watch: Optional[dict] = None       # watch verdict + extra
        self.freshness_s: Optional[float] = None

    @property
    def promoted(self) -> bool:
        return self.outcome == PROMOTED

    def as_dict(self) -> dict:
        return {"candidate_id": self.candidate_id, "outcome": self.outcome,
                "version": self.version, "report": self.report,
                "gate": self.gate, "canary": self.canary,
                "watch": self.watch, "freshness_s": self.freshness_s}


class PromotionManager:
    """Drives the promotion state machine for one served model name
    (module docstring).  ``canary_fraction=None`` (or
    ``canary_min_requests=0``) skips the canary phase;
    ``watch_window_s=0`` swaps-and-commits immediately (no rollback
    window).  ``self_probe`` routes the eval set through the REAL
    serving path during canary and watch — with no external traffic the
    state machine still gathers evidence, and the probes co-batch with
    live requests when there are any."""

    def __init__(self, engine: ServingEngine,
                 model_name: str = DEFAULT_MODEL, *,
                 eval_set: Optional[DataSet] = None,
                 gate_rules: Optional[List[HealthRule]] = None,
                 watch_rules: Optional[List[HealthRule]] = None,
                 canary_fraction: Optional[float] = 0.25,
                 canary_min_requests: int = 8,
                 canary_timeout_s: float = 10.0,
                 canary_max_error_rate: float = 0.0,
                 watch_window_s: float = 1.0,
                 watch_poll_s: float = 0.05,
                 watch_min_requests: int = 1,
                 watch_max_error_rate: float = 0.05,
                 self_probe: Optional[bool] = None,
                 probe_deadline_s: float = 5.0,
                 example: Optional[np.ndarray] = None,
                 registry=None, sleep=time.sleep):
        self.engine = engine
        self.model_name = model_name
        self.eval_set = eval_set
        self.gate_rules = (list(gate_rules) if gate_rules is not None
                           else default_gate_rules())
        self.watch_rules = (list(watch_rules) if watch_rules is not None
                            else default_watch_rules(
                                max_error_rate=watch_max_error_rate,
                                min_requests=watch_min_requests))
        self.canary_fraction = canary_fraction
        self.canary_min_requests = int(canary_min_requests)
        self.canary_timeout_s = float(canary_timeout_s)
        self.canary_max_error_rate = float(canary_max_error_rate)
        self.watch_window_s = float(watch_window_s)
        self.watch_poll_s = float(watch_poll_s)
        self.self_probe = (self_probe if self_probe is not None
                           else eval_set is not None)
        self.probe_deadline_s = float(probe_deadline_s)
        self.example = example
        self._registry = registry
        self._sleep = sleep
        self._canary_seed = 0

    # -------------------------------------------------------------- plumbing
    def _reg(self):
        if self._registry is not None:
            return self._registry
        from deeplearning4j_tpu.observability import get_registry

        return get_registry()

    def _count(self, outcome: str) -> None:
        self._reg().counter(
            _PROMOTIONS, "Candidate models by promotion outcome: promoted "
            "(swap committed), rejected (failed the eval gate, never "
            "touched the registry), canary_rejected (regressed on canary "
            "traffic), rolled_back (post-swap watch window regressed — "
            "previous version restored), rollback_failed (watch regressed "
            "but the retained version was gone — candidate left serving, "
            "operator attention required)", labels=("model", "outcome")
        ).inc(model=self.model_name, outcome=outcome)

    def _example(self) -> Optional[np.ndarray]:
        if self.example is not None:
            return self.example
        if self.eval_set is not None:
            return np.asarray(self.eval_set.features[0], np.float32)
        return None

    # ------------------------------------------------------------- the walk
    def consider(self, candidate, candidate_id: str = "candidate", *,
                 event_ts: Optional[float] = None) -> PromotionResult:
        """Walk ``candidate`` through the full state machine and return
        where it ended up.  ``event_ts`` (publish wall-time of the oldest
        stream event the candidate learned from) feeds the
        ``dl4j_online_model_freshness_seconds`` gauge on promotion."""
        res = PromotionResult(candidate_id)
        try:
            res.report = self._evaluate(candidate, candidate_id)
        except Exception as e:
            # a candidate that cannot even be scored offline is broken —
            # an outcome, not a pipeline crash
            return self._reject_broken(res, candidate_id, "evaluate", e)

        verdict = HealthEvaluator(
            self.gate_rules, component=f"gate.{self.model_name}",
            registry=self._reg()).evaluate(extra=res.report)
        res.gate = verdict.to_dict()
        if not verdict.healthy:
            res.outcome = REJECTED
            self._count(REJECTED)
            get_flight_recorder().record(
                "promotion_rejected", model=self.model_name,
                candidate=candidate_id,
                failed_rules=[r["name"] for r in verdict.failing],
                loss=res.report.get("loss"),
                active_loss=res.report.get("active_loss"))
            logger.warning(
                "candidate %s REJECTED at the gate (%s) — registry "
                "untouched", candidate_id,
                ", ".join(r["name"] for r in verdict.failing))
            return res

        if self.canary_fraction and self.canary_min_requests > 0:
            try:
                ok, stats = self._canary_phase(candidate, candidate_id)
            except Exception as e:
                # a candidate that cannot even start its canary (warmup
                # forward failed, unloadable artifact) is an OUTCOME, not
                # a pipeline crash — the primary version never stopped
                # serving
                return self._reject_broken(res, candidate_id, "canary", e)
            res.canary = stats
            if not ok:
                res.outcome = CANARY_REJECTED
                self._count(CANARY_REJECTED)
                get_flight_recorder().record(
                    "canary_rejected", model=self.model_name,
                    candidate=candidate_id,
                    error_rate=stats.get("error_rate"),
                    requests=stats.get("requests"))
                logger.warning(
                    "candidate %s rejected on canary traffic "
                    "(error_rate=%.3f over %d requests)", candidate_id,
                    stats.get("error_rate", 0.0), stats.get("requests", 0))
                return res

        try:
            mv = self.engine.deploy(self.model_name, candidate,
                                    example=self._example(), retain_old=True)
        except Exception as e:
            # deploy aborts BEFORE activation on a broken warmup forward —
            # the old version is intact, so classify and move on
            return self._reject_broken(res, candidate_id, "deploy", e)
        res.version = mv.version
        get_flight_recorder().record(
            "promotion_swap", model=self.model_name, candidate=candidate_id,
            version=mv.version)

        if self.watch_window_s > 0:
            watch_verdict, extra = self._watch_phase()
            res.watch = {"verdict": watch_verdict.to_dict(), **extra}
            if not watch_verdict.healthy:
                try:
                    self.engine.rollback(self.model_name)
                except ModelNotFoundError as e:
                    # the rollback window was closed under us (a
                    # concurrent manual deploy/commit) — the regressed
                    # candidate is still serving and an operator must
                    # know; an uncaught raise here would kill the
                    # pipeline loop instead
                    res.outcome = ROLLBACK_FAILED
                    self._count(ROLLBACK_FAILED)
                    get_flight_recorder().record(
                        "rollback_failed", model=self.model_name,
                        candidate=candidate_id, version=mv.version,
                        error=str(e),
                        failed_rules=[r["name"]
                                      for r in watch_verdict.failing])
                    logger.error(
                        "candidate %s (v%d) FAILED its watch but cannot "
                        "be rolled back (%s) — still serving", candidate_id,
                        mv.version, e)
                    return res
                res.outcome = ROLLED_BACK
                self._count(ROLLED_BACK)
                logger.warning(
                    "candidate %s (v%d) ROLLED BACK: post-swap watch "
                    "failed (%s)", candidate_id, mv.version,
                    ", ".join(r["name"] for r in watch_verdict.failing))
                return res

        self.engine.commit_swap(self.model_name)
        res.outcome = PROMOTED
        self._count(PROMOTED)
        if event_ts is not None:
            res.freshness_s = max(0.0, time.time() - float(event_ts))
            self._reg().gauge(
                _FRESHNESS, "Seconds from the publish timestamp of the "
                "oldest stream event in the last promoted window to the "
                "moment its model committed into serving (end-to-end "
                "stream-to-serving staleness)", labels=("model",)
            ).set(res.freshness_s, model=self.model_name)
        get_flight_recorder().record(
            "promotion_committed", model=self.model_name,
            candidate=candidate_id, version=mv.version,
            freshness_s=res.freshness_s)
        logger.info("candidate %s promoted as %s", candidate_id, mv.key)
        return res

    def _reject_broken(self, res: PromotionResult, candidate_id: str,
                       stage: str, err: BaseException) -> PromotionResult:
        res.outcome = REJECTED
        res.report.setdefault("broken", f"{stage}: {err!r}")
        self._count(REJECTED)
        get_flight_recorder().record(
            "promotion_rejected", model=self.model_name,
            candidate=candidate_id, failed_rules=[f"broken_{stage}"],
            error=repr(err))
        logger.warning("candidate %s REJECTED: %s failed: %r",
                       candidate_id, stage, err)
        return res

    # --------------------------------------------------------------- phases
    def _evaluate(self, candidate, candidate_id: str) -> Dict[str, Any]:
        report: Dict[str, Any] = {"candidate_id": candidate_id}
        ds = self.eval_set
        if ds is None:
            return report
        x, y = ds.features, ds.labels
        fm, lm = ds.features_mask, ds.labels_mask
        report["loss"] = float(candidate.score(x, y, fmask=fm, lmask=lm))
        try:
            active = self.engine.models.active(self.model_name).model
            if active is not None:
                report["active_loss"] = float(
                    active.score(x, y, fmask=fm, lmask=lm))
        except Exception:
            pass    # no active baseline (first deploy) — relative rules pass
        if np.ndim(y) == 2 and np.shape(y)[1] >= 2:
            try:
                from deeplearning4j_tpu.evaluation import Evaluation

                ev = Evaluation()
                ev.eval(y, np.asarray(candidate.output(x)), mask=lm)
                report["accuracy"] = float(ev.accuracy())
            except Exception:
                pass    # non-classification outputs: loss rules still gate
        return report

    def _canary_phase(self, candidate, candidate_id: str):
        self._canary_seed += 1
        self.engine.start_canary(
            self.model_name, candidate, fraction=float(self.canary_fraction),
            example=self._example(), seed=self._canary_seed)
        deadline = time.monotonic() + self.canary_timeout_s
        probe_failed = None
        try:
            while time.monotonic() < deadline:
                if self.self_probe:
                    verdict, detail = self._probe()
                    if verdict is False:
                        # NaN/garbage outputs don't raise, so transport
                        # tallies alone would score them "ok" — the probe
                        # verdict is canary evidence too
                        probe_failed = detail
                        break
                stats = self.engine.canary_stats(self.model_name)
                # "judged" excludes sheds: a full queue is the engine's
                # load, not canary evidence — 8 shed requests must not
                # satisfy the evidence threshold with error_rate 0
                if (stats is not None
                        and stats["judged"] >= self.canary_min_requests):
                    break
                self._sleep(self.watch_poll_s)
        finally:
            stats = self.engine.stop_canary(self.model_name) or {}
        if probe_failed is not None:
            return False, dict(stats, probe_detail=probe_failed)
        if not stats.get("judged"):
            # a quiet (or fully shed) canary produced no evidence either
            # way; the watch window after the swap is the backstop
            return True, dict(stats, detail="no judged canary traffic")
        ok = stats["error_rate"] <= self.canary_max_error_rate
        return ok, stats

    def _watch_phase(self):
        base = self._status_counts()
        evaluator = HealthEvaluator(
            self.watch_rules, component=f"watch.{self.model_name}",
            registry=self._reg())
        deadline = time.monotonic() + self.watch_window_s
        probe_ok, probe_detail = True, None
        while True:
            extra = self._watch_extra(base, probe_ok, probe_detail)
            verdict = evaluator.evaluate(extra=extra)
            if not verdict.healthy or time.monotonic() >= deadline:
                return verdict, extra
            if self.self_probe:
                v, probe_detail = self._probe()
                probe_ok = v is not False   # None (shed) is inconclusive
            self._sleep(self.watch_poll_s)

    def _probe(self):
        """One eval-set round trip through the REAL serving path.
        ``False`` only on MODEL-quality failures (a raise, wrong shape,
        non-finite outputs); a shed/deadline is the ENGINE's load, so it
        returns ``None`` (inconclusive) — the error-rate rules own that
        signal, and a load spike must not masquerade as a bad model."""
        ds = self.eval_set
        if ds is None:
            return True, "no eval set; probe skipped"
        try:
            out = self.engine.predict(ds.features, model=self.model_name,
                                      deadline_s=self.probe_deadline_s)
        except ServingError as e:
            return None, f"probe inconclusive (shed): {e}"
        except Exception as e:
            return False, f"probe raised: {e!r}"
        out = np.asarray(out)
        if len(out) != len(ds.features):
            return False, (f"probe returned {len(out)} rows for "
                           f"{len(ds.features)} inputs")
        if not np.isfinite(out).all():
            return False, "probe outputs contain NaN/Inf"
        return True, "probe ok"

    def _status_counts(self) -> Dict[str, float]:
        # per-MODEL outcomes (engine-internal tally): the shared
        # requests counter has no model label, and another model's
        # errors during the window must not roll this candidate back
        # (nor may its ok-traffic dilute a real regression)
        return {k: float(v) for k, v in
                self.engine.status_counts(self.model_name).items()}

    def _watch_extra(self, base: Dict[str, float], probe_ok: bool,
                     probe_detail) -> Dict[str, Any]:
        now = self._status_counts()
        delta = {k: now.get(k, 0.0) - base.get(k, 0.0)
                 for k in set(now) | set(base)}
        # same "judged" convention as the canary: sheds are visible in
        # ``statuses`` but appear in neither the evidence count nor the
        # error-rate denominator — 95 queue_full deltas must not dilute
        # 2 failures out of 5 judged requests below the SLO
        bad = max(0.0, delta.get("error", 0.0)) + \
            max(0.0, delta.get("deadline", 0.0))
        judged = bad + max(0.0, delta.get("ok", 0.0))
        return {
            "requests": int(judged), "bad": int(bad),
            "error_rate": (bad / judged) if judged else 0.0,
            "statuses": {k: v for k, v in delta.items() if v},
            "probe_ok": probe_ok, "probe_detail": probe_detail,
        }
