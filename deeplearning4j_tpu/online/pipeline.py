"""The continuous-learning loop: stream -> windowed fit -> checkpoint ->
promotion -> serving, hardened at every stage.

One ``OnlineLearningPipeline`` closes the production loop the rest of
the stack provides the pieces for: it consumes DataSet messages from a
``streaming.pubsub`` topic (in-process broker or the HTTP transport),
trains the live model incrementally one WINDOW of messages at a time
(each window is an ``AsyncDataSetIterator`` mini-epoch through the real
fit loop, fault-injection hooks and all), snapshots every window
boundary through the PR-5 ``CheckpointManager``, then walks the window's
candidate through the ``PromotionManager`` state machine — evaluate,
SLO gate, canary, zero-drop hot-swap, post-swap watch, automatic
rollback (docs/online.md).

Stage-by-stage failure containment:

- **bad records** never reach ``fit``: the ``StreamConsumer`` validates
  on consume and dead-letters offenders to the quarantine topic;
- **stream outages** ride the ``RetryPolicy`` (the HTTP transport
  resumes its subscription after a broker restart);
- **trainer crashes** mid-window restore the last committed window
  boundary from the ``CheckpointManager`` and replay the SAME window
  from memory — committed windows are never re-consumed from the
  stream, and the stream is never re-read;
- **regressed candidates** are refused by the gate (flight event names
  them) and — with ``revert_on_reject`` — the trainer itself is
  restored from the last accepted artifact, so one poisoned-but-valid
  window can't silently steer all later candidates;
- **post-swap regressions** roll serving back to the retained previous
  version automatically; the trainer reverts with it.
"""

from __future__ import annotations

import logging
import os
import tempfile
import threading
from typing import Any, Dict, List, Optional, Tuple

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import (
    AsyncDataSetIterator, DataSetIterator,
)
from deeplearning4j_tpu.observability.flightrecorder import (
    get_flight_recorder,
)
from deeplearning4j_tpu.online.consumer import StreamConsumer
from deeplearning4j_tpu.online.promotion import PromotionManager
from deeplearning4j_tpu.resilience.retry import RetryPolicy
from deeplearning4j_tpu.serving.admission import ModelNotFoundError
from deeplearning4j_tpu.serving.engine import DEFAULT_MODEL, ServingEngine

_WINDOWS = "dl4j_online_windows_total"

logger = logging.getLogger("deeplearning4j_tpu.online")


class _WindowIterator(DataSetIterator):
    """Resettable iterator over one window's in-memory DataSets — the
    replayable unit the crash-resume path re-fits after a restore."""

    def __init__(self, datasets: List[DataSet]):
        self._datasets = list(datasets)
        self._i = 0

    def next(self) -> DataSet:
        ds = self._datasets[self._i]
        self._i += 1
        return ds

    def has_next(self) -> bool:
        return self._i < len(self._datasets)

    def reset(self) -> None:
        self._i = 0

    def batch(self) -> int:
        return len(self._datasets[0]) if self._datasets else 0

    def async_supported(self) -> bool:
        return True


class OnlineLearningPipeline:
    """See module docstring.  Minimal use::

        engine = ServingEngine(model, example=example).start()
        pipe = OnlineLearningPipeline(
            net, engine, topic="train", broker=broker,
            checkpoint_manager=CheckpointManager(dir),
            promotion=PromotionManager(engine, eval_set=holdout))
        summary = pipe.run(max_windows=10)   # or start()/stop()

    ``net`` is the TRAINING model (either fit-loop facade); the engine
    serves independent copies loaded from each window's candidate
    artifact, so training never mutates weights a request might be
    reading.
    """

    def __init__(self, net, engine: ServingEngine, *, topic: str,
                 broker=None, url: Optional[str] = None,
                 model_name: str = DEFAULT_MODEL,
                 checkpoint_manager=None,
                 retry_policy: Optional[RetryPolicy] = None,
                 promotion: Optional[PromotionManager] = None,
                 window_size: int = 4, prefetch: int = 2,
                 poll_timeout_s: float = 1.0,
                 max_window_retries: int = 2,
                 revert_on_reject: bool = True,
                 workdir: Optional[str] = None,
                 sub_id: str = "online", registry=None):
        self.net = net
        self.engine = engine
        self.model_name = model_name
        self.cm = checkpoint_manager
        self.retry = retry_policy if retry_policy is not None else \
            RetryPolicy(max_retries=2, base_delay_s=0.05, max_delay_s=1.0,
                        component="online", registry=registry)
        self.promotion = promotion if promotion is not None else \
            PromotionManager(engine, model_name, registry=registry)
        self.window_size = int(window_size)
        self.prefetch = int(prefetch)
        self.poll_timeout_s = float(poll_timeout_s)
        self.max_window_retries = int(max_window_retries)
        self.revert_on_reject = bool(revert_on_reject)
        self.consumer = StreamConsumer(
            topic, broker=broker, url=url, sub_id=sub_id,
            retry_policy=self.retry, registry=registry)
        if workdir is None:
            workdir = (os.path.join(self.cm.directory, "candidates")
                       if self.cm is not None
                       else tempfile.mkdtemp(prefix="dl4j-online-"))
        self.workdir = workdir
        os.makedirs(self.workdir, exist_ok=True)
        self._registry = registry
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._window_index = 0
        self._anchored = False
        self._last_good_zip: Optional[str] = None
        self.results: List[Dict[str, Any]] = []

    # -------------------------------------------------------------- plumbing
    def _reg(self):
        if self._registry is not None:
            return self._registry
        from deeplearning4j_tpu.observability import get_registry

        return get_registry()

    def _count_window(self, status: str) -> None:
        self._reg().counter(
            _WINDOWS, "Online-learning training windows by outcome "
            "(mutually exclusive — the label sums to the window count): "
            "trained (clean fit + checkpoint committed), retried (a "
            "trainer crash restored the window boundary and the replay "
            "from memory succeeded), failed (retry budget exhausted — "
            "window dropped, trainer restored)", labels=("status",)
        ).inc(status=status)

    def _write_zip(self, tag: str) -> str:
        from deeplearning4j_tpu.models import serialization

        path = os.path.join(self.workdir, f"candidate-{tag}.zip")
        serialization.write_model(self.net, path)
        return path

    def _load_candidate(self, path: str):
        from deeplearning4j_tpu.models import serialization

        return serialization.load_model(path, load_updater=False)

    def _load_params_from(self, path: str) -> None:
        """Restore the TRAINER's weights/updater/state from an accepted
        artifact WITHOUT rewinding the iteration counter: step numbers
        stay monotonic, so window checkpoints never collide with a stale
        committed directory from a rejected timeline."""
        from deeplearning4j_tpu.models import serialization

        m = serialization.load_model(path, load_updater=True)
        self.net.params = m.params
        self.net.updater_state = m.updater_state
        self.net.net_state = m.net_state

    # ----------------------------------------------------------------- setup
    def _ensure_anchor(self) -> None:
        """First-run duties: make sure serving has an active version of
        ``model_name`` (deploying the trainer's current state when not),
        keep its artifact as the revert target, and commit an anchor
        checkpoint so a crash in the FIRST window has a restore point."""
        if self._anchored:
            return
        anchor_zip = self._write_zip("anchor")
        try:
            self.engine.models.active(self.model_name)
        except ModelNotFoundError:
            self.engine.deploy(self.model_name, self._load_candidate(
                anchor_zip), example=self.promotion._example())
        self._last_good_zip = anchor_zip
        if self.cm is not None:
            self.cm.save(self.net, trigger="explicit", block=True)
        self._anchored = True

    # ------------------------------------------------------------ collection
    def _collect_window(self) -> List[Tuple[DataSet, Dict[str, Any]]]:
        items: List[Tuple[DataSet, Dict[str, Any]]] = []
        while len(items) < self.window_size and not self._stop.is_set():
            got = self.consumer.poll_dataset(timeout=self.poll_timeout_s)
            if got is None:
                break       # topic quiet: train the partial window, if any
            items.append(got)
        return items

    # -------------------------------------------------------------- training
    def _train_window(self, datasets: List[DataSet], wid: str) -> bool:
        """Fit one window through the real loop (retry policy + fault
        hooks inside).  A trainer crash restores the window boundary from
        the CheckpointManager and replays the SAME in-memory window — the
        stream is not re-consumed.  Returns False when the retry budget
        is exhausted (window dropped, trainer restored to the
        boundary)."""
        start_step = int(getattr(self.net, "iteration", 0))
        attempts = 0
        while True:
            it = AsyncDataSetIterator(_WindowIterator(datasets),
                                      self.prefetch)
            try:
                self.net.fit(it, retry_policy=self.retry)
                # statuses are mutually exclusive so the label sums to
                # the window count: a crash-recovered window is
                # "retried", a clean one "trained"
                self._count_window("retried" if attempts else "trained")
                return True
            except Exception as e:
                attempts += 1
                get_flight_recorder().record(
                    "online_trainer_crash", window=wid, attempt=attempts,
                    error=repr(e))
                logger.warning(
                    "trainer crashed in %s (attempt %d/%d): %r", wid,
                    attempts, self.max_window_retries, e)
                self._restore_boundary(start_step)
                if attempts > self.max_window_retries:
                    self._count_window("failed")
                    get_flight_recorder().record(
                        "online_window_failed", window=wid, error=repr(e))
                    logger.error(
                        "window %s dropped after %d attempts", wid, attempts)
                    return False
            finally:
                self._drain(it)

    def _restore_boundary(self, step: int) -> None:
        """Auto-resume: restore the last committed window boundary (the
        exact ``step`` when its checkpoint survives retention, else the
        newest valid one)."""
        if self.cm is None:
            return      # no manager: replay on top (documented best-effort)
        try:
            self.cm.restore(self.net, step=step)
        except FileNotFoundError:
            try:
                self.cm.restore(self.net)
            except FileNotFoundError:
                logger.warning("no valid checkpoint to restore; replaying "
                               "window on the current state")

    @staticmethod
    def _drain(it: AsyncDataSetIterator) -> None:
        """Exhaust an abandoned window iterator so its producer thread
        exits instead of blocking on the bounded prefetch queue."""
        try:
            while it.has_next():
                it.next()
        except Exception:
            pass

    # ------------------------------------------------------------ the window
    def process_window(
            self, items: List[Tuple[DataSet, Dict[str, Any]]]
    ) -> Dict[str, Any]:
        """Train one window and walk its candidate through promotion;
        returns the per-window record appended to ``results``."""
        self._ensure_anchor()
        self._window_index += 1
        wid = f"window-{self._window_index}"
        datasets = [ds for ds, _ in items]
        tss = [m.get("ts") for _, m in items
               if isinstance(m.get("ts"), (int, float))]
        event_ts = min(tss) if tss else None

        if not self._train_window(datasets, wid):
            return self._record(wid, {"outcome": "window_failed"})
        if self.cm is not None:
            self.cm.save(self.net, trigger="explicit", block=True)

        tag = f"{self._window_index:05d}"
        zip_path = self._write_zip(tag)
        candidate = self._load_candidate(zip_path)
        cid = f"{wid}@iter{int(getattr(self.net, 'iteration', 0))}"
        res = self.promotion.consider(candidate, cid, event_ts=event_ts)

        if res.promoted:
            self._replace_good_zip(zip_path)
        else:
            if self.revert_on_reject and self._last_good_zip is not None:
                self._load_params_from(self._last_good_zip)
                if self.cm is not None:
                    # anchor the reverted state so a crash in the next
                    # window restores GOOD weights, not the rejected ones
                    self.cm.save(self.net, trigger="explicit", block=True)
                get_flight_recorder().record(
                    "online_training_reverted", window=wid,
                    to=os.path.basename(self._last_good_zip),
                    outcome=res.outcome)
            self._remove(zip_path)
        return self._record(wid, {"outcome": res.outcome,
                                  "promotion": res.as_dict(),
                                  "event_ts": event_ts,
                                  "freshness_s": res.freshness_s,
                                  "records": len(items)})

    def _replace_good_zip(self, zip_path: str) -> None:
        old = self._last_good_zip
        self._last_good_zip = zip_path
        if old is not None and old != zip_path:
            self._remove(old)

    @staticmethod
    def _remove(path: str) -> None:
        try:
            os.remove(path)
        except OSError:
            pass

    def _record(self, wid: str, fields: Dict[str, Any]) -> Dict[str, Any]:
        rec = {"window": wid, **fields}
        self.results.append(rec)
        return rec

    # ------------------------------------------------------------------- run
    def run(self, max_windows: Optional[int] = None,
            stop_on_idle: bool = True) -> Dict[str, Any]:
        """Blocking consume-train-promote loop; returns after
        ``max_windows`` windows, when the topic stays quiet past
        ``poll_timeout_s`` (unless ``stop_on_idle=False`` — the
        continuous mode ``start()`` uses, where a traffic lull must NOT
        silently end the loop), or on ``stop()``.  The summary counts
        every outcome and carries the freshness of promoted windows."""
        self._ensure_anchor()
        processed = 0
        while not self._stop.is_set():
            items = self._collect_window()
            if not items:
                if stop_on_idle:
                    break
                continue    # _collect_window already waited poll_timeout_s
            self.process_window(items)
            processed += 1
            if max_windows is not None and processed >= max_windows:
                break
        return self.summary()

    def summary(self) -> Dict[str, Any]:
        outcomes: Dict[str, int] = {}
        freshness = []
        for r in self.results:
            outcomes[r["outcome"]] = outcomes.get(r["outcome"], 0) + 1
            if r.get("freshness_s") is not None:
                freshness.append(r["freshness_s"])
        return {
            "windows": len(self.results),
            "outcomes": outcomes,
            "promoted": outcomes.get("promoted", 0),
            "quarantined": self.consumer.quarantined,
            "records_delivered": self.consumer.delivered,
            "freshness_s": freshness,
            "active_version": self._active_version(),
        }

    def _active_version(self) -> Optional[int]:
        try:
            return self.engine.models.active(self.model_name).version
        except Exception:
            return None

    # -------------------------------------------------------------- threaded
    def start(self, max_windows: Optional[int] = None
              ) -> "OnlineLearningPipeline":
        """Run the loop on a background thread in CONTINUOUS mode: a
        traffic lull keeps polling instead of ending the loop — a
        pipeline the operator believes is live must never exit silently
        on a quiet second.  ``stop()`` ends it; any crash is logged and
        flight-recorded before the thread dies."""
        if self._thread is not None and self._thread.is_alive():
            raise RuntimeError("pipeline already running")
        self._stop.clear()

        def _run():
            try:
                self.run(max_windows=max_windows, stop_on_idle=False)
            except BaseException as e:   # noqa: BLE001 — last-resort visibility
                get_flight_recorder().record(
                    "online_pipeline_died", error=repr(e))
                logger.exception("online pipeline thread died")
                raise
            finally:
                logger.info("online pipeline thread exiting (%s)",
                            "stopped" if self._stop.is_set()
                            else "max_windows reached")

        self._thread = threading.Thread(
            target=_run, name="dl4j-online-pipeline", daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout: float = 30.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            if self._thread.is_alive():
                # join timed out mid-window: keep the handle so a later
                # start() refuses instead of reviving the OLD loop by
                # clearing the _stop event it still polls (two threads
                # on one net/consumer would interleave windows)
                logger.warning(
                    "pipeline thread still finishing its window after "
                    "%.1fs; start() will refuse until it exits", timeout)
            else:
                self._thread = None
