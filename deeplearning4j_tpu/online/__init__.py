"""Continuous online learning: stream -> windowed incremental fit ->
checkpointed candidates -> SLO-gated promotion with canary, zero-drop
hot-swap, post-swap watch, and automatic rollback.

The production loop the rest of the stack provides the pieces for
(streaming pub/sub, resilience, serving, health SLOs) — one pipeline
that ingests live traffic, learns from it, and redeploys itself
continuously, with every stage hardened against its real failure mode.
See docs/online.md.
"""

from deeplearning4j_tpu.online.consumer import StreamConsumer
from deeplearning4j_tpu.online.pipeline import OnlineLearningPipeline
from deeplearning4j_tpu.online.promotion import (
    CANARY_REJECTED, PROMOTED, REJECTED, ROLLBACK_FAILED, ROLLED_BACK,
    PromotionManager, PromotionResult, default_gate_rules,
    default_watch_rules,
)

__all__ = [
    "CANARY_REJECTED", "PROMOTED", "REJECTED", "ROLLBACK_FAILED",
    "ROLLED_BACK", "OnlineLearningPipeline", "PromotionManager",
    "PromotionResult", "StreamConsumer", "default_gate_rules",
    "default_watch_rules",
]
