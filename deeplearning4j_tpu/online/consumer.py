"""Hardened stream consumer: validation, quarantine, transport retry.

The trusting path (``NDArrayConsumer.poll`` -> ``fit``) has three silent
failure modes this class closes:

- **poisoned records** — a NaN/Inf payload, a bit-flipped base64 string,
  or a shape-lying envelope would corrupt a whole training window.  Every
  message is decoded through ``serde.consume_dataset_json`` (strict
  validation); anything raising ``BadRecordError`` is published to a
  quarantine (dead-letter) topic with its reason and counted in
  ``dl4j_stream_quarantined_total{topic,reason}`` — the window never sees
  it, and the original payload is preserved verbatim for the runbook
  (docs/online.md) to replay after a fix;
- **transport outages** — the HTTP transport raises connection errors
  while the broker endpoint is dead or restarting; polls ride the PR-5
  ``RetryPolicy`` (exponential backoff, seeded jitter), and because the
  broker keys HTTP subscriptions by ``sub=<id>``, a consumer that backed
  off through a restart resumes the SAME subscription — no duplicated,
  no silently skipped messages for anything published after the broker
  came back;
- **invisible lag** — ``delivered`` / ``quarantined`` counters expose the
  consumer's position, and the broker side counts its own overflow drops
  (``dl4j_stream_dropped_total{topic}``).
"""

from __future__ import annotations

import json
import logging
import queue
import time
import urllib.error
import urllib.request
from collections import deque
from typing import Any, Dict, Optional, Tuple

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.resilience.retry import RetryPolicy, TransientError
from deeplearning4j_tpu.streaming.pubsub import MessageBroker
from deeplearning4j_tpu.streaming.serde import (
    BadRecordError, consume_dataset_json,
)

_QUARANTINED = "dl4j_stream_quarantined_total"
_QUARANTINE_WARN_INTERVAL_S = 30.0

logger = logging.getLogger("deeplearning4j_tpu.online")


class StreamConsumer:
    """Validated, quarantining, retrying consumer of DataSet messages
    (module docstring).  Exactly one of ``broker`` (in-process) or
    ``url`` (HTTP transport) is required, mirroring ``NDArrayConsumer``.
    """

    def __init__(self, topic: str, broker: Optional[MessageBroker] = None,
                 url: Optional[str] = None, sub_id: str = "online",
                 quarantine_topic: Optional[str] = None,
                 retry_policy: Optional[RetryPolicy] = None,
                 dead_letter_capacity: int = 256,
                 registry=None, timeout: float = 5.0):
        if (broker is None) == (url is None):
            raise ValueError("exactly one of broker/url required")
        self.topic = topic
        self.broker = broker
        self.url = url.rstrip("/") if url else None
        self.sub_id = sub_id
        self.timeout = float(timeout)
        self.quarantine_topic = quarantine_topic or f"{topic}.quarantine"
        self.retry = retry_policy
        self._registry = registry
        self._queue = broker.subscribe(topic) if broker is not None else None
        self._last_quarantine_warn: Optional[float] = None
        self.delivered = 0          # valid DataSets handed to the caller
        self.quarantined = 0
        # the broker is fire-and-forget (no retention): a dead letter
        # published before anyone subscribed the quarantine topic would
        # be lost — so the consumer ALSO retains the newest envelopes
        # locally, where the runbook can always find them
        self.dead_letters: "deque[Dict[str, Any]]" = deque(
            maxlen=int(dead_letter_capacity))

    def _reg(self):
        if self._registry is not None:
            return self._registry
        from deeplearning4j_tpu.observability import get_registry

        return get_registry()

    # ------------------------------------------------------------ transport
    def _poll_once(self, timeout: float) -> Optional[str]:
        """One raw poll: the message text, or None when the topic stayed
        quiet.  HTTP connection failures surface as ``TransientError`` so
        the retry policy classifies them without string matching."""
        if self._queue is not None:
            try:
                return self._queue.get(timeout=timeout)
            except queue.Empty:
                return None
        req = (f"{self.url}/poll/{self.topic}?sub={self.sub_id}"
               f"&timeout={timeout}")
        try:
            with urllib.request.urlopen(req, timeout=timeout + 5) as resp:
                if resp.status == 204:
                    return None
                return resp.read().decode()
        except (urllib.error.URLError, ConnectionError, TimeoutError,
                OSError) as e:
            raise TransientError(
                f"broker poll on {self.url!r} failed: {e}") from e

    def poll_raw(self, timeout: Optional[float] = None) -> Optional[str]:
        """Raw message text with transport retries (dead broker endpoint
        -> exponential backoff until it answers again or the budget is
        exhausted)."""
        timeout = self.timeout if timeout is None else timeout
        if self.retry is None:
            return self._poll_once(timeout)
        return self.retry.run(lambda: self._poll_once(timeout),
                              description=f"poll {self.topic}")

    # ------------------------------------------------------------- datasets
    def poll_dataset(self, timeout: Optional[float] = None
                     ) -> Optional[Tuple[DataSet, Dict[str, Any]]]:
        """The validated consume: ``(DataSet, meta)`` for the next GOOD
        record, or None when the topic stays quiet for ``timeout``.  Bad
        records are quarantined and skipped WITHOUT consuming the time
        budget's patience — the poll keeps going until the deadline."""
        timeout = self.timeout if timeout is None else timeout
        deadline = time.monotonic() + timeout
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return None
            raw = self.poll_raw(timeout=remaining)
            if raw is None:
                return None
            try:
                ds, meta = consume_dataset_json(raw)
            except BadRecordError as e:
                self.quarantine(raw, e)
                continue
            except Exception as e:
                # defense in depth: ANY record-shaped failure quarantines —
                # one poisoned message must never kill the consumer loop
                self.quarantine(raw, BadRecordError(
                    f"undecodable record: {e!r}", reason="bad_envelope"))
                continue
            self.delivered += 1
            return ds, meta

    # ----------------------------------------------------------- quarantine
    def quarantine(self, raw: str, err: BadRecordError) -> None:
        """Dead-letter one bad record: preserve the payload verbatim on
        the quarantine topic (wrapped with its reason + timestamp), count
        it, flight-record it, and warn (rate-limited)."""
        self.quarantined += 1
        reason = getattr(err, "reason", "invalid")
        record = {
            "reason": reason, "error": str(err)[:300],
            "topic": self.topic, "quarantined_at": time.time(),
            "payload": raw,
        }
        self.dead_letters.append(record)
        envelope = json.dumps(record)
        try:
            if self.broker is not None:
                self.broker.publish(self.quarantine_topic, envelope)
            else:
                req = urllib.request.Request(
                    f"{self.url}/publish/{self.quarantine_topic}",
                    data=envelope.encode(),
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=self.timeout):
                    pass    # drain + close; a leaked fd per dead letter
                    # would turn a poisoned-record flood into EMFILE
        except Exception:
            # the dead-letter write is best-effort: a broker outage here
            # must not turn ONE bad record into a dead consumer — the
            # counter and flight event still record the loss
            logger.debug("quarantine publish failed", exc_info=True)
        self._reg().counter(
            _QUARANTINED, "Stream records rejected by consume-side "
            "validation and published to the quarantine (dead-letter) "
            "topic instead of reaching fit, by topic and reason",
            labels=("topic", "reason")).inc(topic=self.topic, reason=reason)
        from deeplearning4j_tpu.observability import get_flight_recorder

        get_flight_recorder().record(
            "stream_quarantined", topic=self.topic, reason=reason,
            error=str(err)[:200])
        now = time.monotonic()
        if (self._last_quarantine_warn is None
                or now - self._last_quarantine_warn
                >= _QUARANTINE_WARN_INTERVAL_S):
            self._last_quarantine_warn = now
            logger.warning(
                "quarantined a bad record from %r (%s: %s) -> %r "
                "[%d quarantined so far]", self.topic, reason, err,
                self.quarantine_topic, self.quarantined)
