"""JAX version compatibility shims for the SPMD modules.

The sharded-training code targets the modern jax surface (``jax.shard_map``
with ``check_vma``, ``lax.pcast`` varying-ness casts).  On older jax
(0.4.x) those live elsewhere or don't exist:

- ``shard_map``: ``jax.experimental.shard_map.shard_map``, whose
  ``check_rep`` kwarg is the predecessor of ``check_vma``.
- ``lax.pcast``: absent.  With replication checking OFF (every call site
  here passes ``check_vma=False``) pcast only adjusts the varying-ness
  *type* of a value, never its data — so the identity function is the
  correct fallback.

Import from here instead of ``jax`` so the parallel/nlp modules load (and
run) on both vintages.
"""

from __future__ import annotations

import jax
from jax import lax as _lax

try:  # modern home
    from jax import shard_map as _shard_map_impl  # type: ignore[attr-defined]
except ImportError:  # 0.4.x experimental home
    from jax.experimental.shard_map import shard_map as _shard_map_impl

# key the kwarg translation on the SIGNATURE, not the import location —
# there are jax vintages with a top-level shard_map that still takes
# check_rep (the check_vma rename landed separately)
import inspect as _inspect

try:
    _HAS_CHECK_VMA = "check_vma" in _inspect.signature(
        _shard_map_impl).parameters
except (TypeError, ValueError):  # unintrospectable: assume modern
    _HAS_CHECK_VMA = True

if _HAS_CHECK_VMA:
    shard_map = _shard_map_impl
else:
    def shard_map(f=None, *, mesh, in_specs, out_specs, check_vma=True,
                  **kw):
        if f is None:  # decorator form: shard_map(mesh=..., ...)(f)
            return lambda fn: shard_map(fn, mesh=mesh, in_specs=in_specs,
                                        out_specs=out_specs,
                                        check_vma=check_vma, **kw)
        return _shard_map_impl(f, mesh=mesh, in_specs=in_specs,
                               out_specs=out_specs, check_rep=check_vma,
                               **kw)


if hasattr(_lax, "pcast"):
    pcast = _lax.pcast
else:
    def pcast(x, axes, to=None):
        return x
