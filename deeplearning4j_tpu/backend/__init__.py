from deeplearning4j_tpu.backend.device import (
    default_mesh,
    device_count,
    local_devices,
    dtype_policy,
    slice_mesh,
    DTypePolicy,
)
from deeplearning4j_tpu.backend.rng import KeyStream
