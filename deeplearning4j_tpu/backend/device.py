"""Device/mesh substrate — the TPU-native equivalent of ND4J + AffinityManager.

The reference pins replicas to devices through ND4J's ``AffinityManager``
(``deeplearning4j-nn/.../iterator/AsyncDataSetIterator.java:75-76``) and moves
data host->device implicitly inside every INDArray op. Here the substrate is
JAX itself: arrays are ``jax.Array`` in HBM, placement is declarative through
``jax.sharding``. This module is the single place the framework asks "what
hardware do I have and how do I lay a mesh over it".
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Canonical mesh-axis names used across the framework.  Data parallelism is
# always the leading 'data' axis; 'model' shards weights (TP); 'seq' shards
# the time axis (sequence/context parallelism — ring attention).
AXIS_DATA = "data"
AXIS_MODEL = "model"
AXIS_SEQ = "seq"


def local_devices():
    return jax.local_devices()


def device_count() -> int:
    return jax.device_count()


def default_backend() -> str:
    return jax.default_backend()


def default_mesh(
    n_devices: Optional[int] = None,
    *,
    data: Optional[int] = None,
    model: int = 1,
    seq: int = 1,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a named device mesh laid out so that collectives ride ICI.

    Axes: ('data', 'model', 'seq').  By default every device goes to the
    data axis (pure DP — the reference's only parallelism strategy, see
    SURVEY.md §2 parallelism inventory).  TP/SP are first-class axes so
    shardings compose: a (8,) slice can run as data=2, model=2, seq=2.
    """
    if devices is None:
        devices = jax.devices()[: n_devices] if n_devices else jax.devices()
    n = len(devices)
    if data is None:
        if n % (model * seq) != 0:
            raise ValueError(f"{n} devices not divisible by model*seq={model * seq}")
        data = n // (model * seq)
    if data * model * seq != n:
        raise ValueError(f"mesh {data}x{model}x{seq} != {n} devices")
    import numpy as np

    dev_array = np.asarray(devices).reshape(data, model, seq)
    return Mesh(dev_array, (AXIS_DATA, AXIS_MODEL, AXIS_SEQ))


def slice_mesh(
    n_slices: Optional[int] = None,
    *,
    model: int = 1,
    seq: int = 1,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Multi-slice (DCN-spanning) mesh with the standard ('data', 'model',
    'seq') axes, laid out so the expensive hop is crossed ONCE.

    On multi-slice TPU, chips within a slice talk over ICI (fast) and
    slices talk over DCN (slow).  XLA lowers a psum over the data axis to
    a hierarchical all-reduce determined purely by DEVICE ORDER: with each
    slice's chips contiguous along the data axis, the reduction runs
    ring/tree within each slice over ICI first and exchanges one
    slice-level partial over DCN — the scaling-book recipe.  This helper
    groups devices by their ``slice_index`` attribute (real multi-slice
    platforms) or into ``n_slices`` contiguous groups (virtual meshes),
    then hands back a mesh every existing TrainingMaster accepts
    unchanged: hierarchical DP needs no new API, only the right order.

    Model/seq axes are kept INSIDE a slice (their collectives are
    per-layer, far too chatty for DCN): each slice must hold a whole
    model*seq block.  Reference analog: none — the reference's Spark
    aggregation tree (``ParameterAveragingTrainingMaster.java:628-645``)
    is the closest concept, with the driver as the (single) slow hop.
    """
    if devices is None:
        devices = list(jax.devices())
    ordered, per_slice = _group_by_slice(devices, n_slices)
    if per_slice % (model * seq) != 0:
        raise ValueError(
            f"model*seq={model * seq} must divide the {per_slice} "
            "devices of each slice (TP/SP collectives must stay on "
            "ICI — a model/seq group cannot straddle DCN)")
    return default_mesh(devices=ordered, model=model, seq=seq)


def _group_by_slice(devices: Sequence, n_slices: Optional[int]):
    """Order devices slice-contiguously; returns (ordered, per_slice).

    Real multi-slice platforms carry a ``slice_index`` device attribute —
    devices regroup by it (sorted by slice, original order within a
    slice) even when ``jax.devices()`` interleaves slices.  Without the
    attribute (CPU/virtual meshes), devices split into ``n_slices`` equal
    contiguous groups.  Kept as a pure function so the regrouping is
    testable with stub devices.  (Deliberately NOT
    ``mesh_utils.create_hybrid_device_mesh``: that helper exposes DCN as
    a SEPARATE mesh axis, while this layout folds slices into the data
    axis so every existing TrainingMaster works unchanged — hierarchical
    reduction then comes from device order alone.)
    """
    has_attr = [getattr(d, "slice_index", None) for d in devices]
    if all(si is None for si in has_attr):
        k = n_slices or 1
        if len(devices) % k != 0:
            raise ValueError(
                f"{len(devices)} devices (no slice_index attribute — "
                f"virtual slicing) are not divisible into n_slices={k} "
                "equal groups")
        per = len(devices) // k
        return list(devices), per
    groups: dict = {}
    for d, si in zip(devices, has_attr):
        groups.setdefault(si if si is not None else -1, []).append(d)
    if n_slices is not None and len(groups) != n_slices:
        raise ValueError(
            f"n_slices={n_slices} but the platform reports "
            f"{len(groups)} slice(s) (slice_index values: "
            f"{sorted(groups)})")
    sizes = {len(g) for g in groups.values()}
    if len(sizes) != 1:
        raise ValueError("unequal devices per slice: "
                         f"{[len(groups[s]) for s in sorted(groups)]}")
    ordered: list = []
    for si in sorted(groups):
        ordered.extend(groups[si])
    return ordered, sizes.pop()


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def data_sharded(mesh: Mesh) -> NamedSharding:
    """Shard the leading (batch) dim over the data axis."""
    return NamedSharding(mesh, P(AXIS_DATA))


@dataclasses.dataclass(frozen=True)
class DTypePolicy:
    """Mixed-precision policy.

    TPU MXU natively computes bf16 x bf16 -> f32.  The policy keeps params
    and optimizer state in f32 (master weights), casts activations/compute
    to ``compute_dtype``, and accumulates in f32.  The reference is f32/f64
    via ND4J's global dtype (no mixed precision existed); ``float32`` policy
    reproduces that exactly for parity tests.
    """

    param_dtype: jnp.dtype = jnp.float32
    compute_dtype: jnp.dtype = jnp.float32
    accum_dtype: jnp.dtype = jnp.float32

    def cast_input(self, x):
        return jax.tree_util.tree_map(
            lambda a: a.astype(self.compute_dtype)
            if hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating)
            else a,
            x,
        )


_POLICIES = {
    "float32": DTypePolicy(),
    "bfloat16": DTypePolicy(compute_dtype=jnp.bfloat16),
}
_current_policy = _POLICIES[os.environ.get("DL4J_TPU_DTYPE", "float32")]


def dtype_policy() -> DTypePolicy:
    return _current_policy


def set_dtype_policy(name: str) -> DTypePolicy:
    global _current_policy
    _current_policy = _POLICIES[name]
    return _current_policy
