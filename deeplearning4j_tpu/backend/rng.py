"""RNG-key discipline.

The reference uses a stateful seeded RNG threaded through config
(``NeuralNetConfiguration`` seed field) and ND4J's global RandomGenerator.
JAX RNG is explicit-key; ``KeyStream`` is the stateful facade used at the
*edges* (model init, data shuffling) while everything inside jit takes keys
as arguments (e.g. dropout, RBM Gibbs sampling — reference
``nn/layers/feedforward/rbm/RBM.java:223-282`` re-derived key-threaded).
"""

from __future__ import annotations

import jax


class KeyStream:
    """Stateful splitter over a root PRNG key — host-side use only."""

    def __init__(self, seed: int = 0):
        self._key = jax.random.key(seed)

    def next(self) -> jax.Array:
        self._key, sub = jax.random.split(self._key)
        return sub

    def next_n(self, n: int):
        self._key, *subs = jax.random.split(self._key, n + 1)
        return subs
