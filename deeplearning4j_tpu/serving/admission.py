"""Admission control: bounded queue, per-request deadlines, load shedding.

Under overload an unbounded serving queue converts excess traffic into
unbounded latency — every queued request eventually times out client-side
but still costs a forward pass.  The production-correct behaviour is to
REJECT at the door (HTTP 429) the moment the queue exceeds its budget,
fail queued requests whose deadline has already passed without running
them, and fail fast (503) during shutdown so no waiter ever hangs on a
dead dispatcher.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

import numpy as np


class ServingError(RuntimeError):
    """Base class for admission/serving rejections; carries the HTTP
    status the front-end should answer with, and — when raised for a
    specific request — that request's ``trace_id`` so a shed/deadline
    error names the request it killed."""

    http_status = 500
    shed_reason: Optional[str] = None
    trace_id: Optional[str] = None


class QueueFullError(ServingError):
    """Request shed because the pending queue exceeded its budget."""

    http_status = 429
    shed_reason = "queue_full"


class ShuttingDownError(ServingError):
    """Request shed (or failed while queued) because the engine is
    stopping/stopped."""

    http_status = 503
    shed_reason = "shutdown"


class DeadlineExceededError(ServingError):
    """Request failed its deadline — either expired while queued (the
    batcher drops it without running the model) or the waiter timed out
    (e.g. the dispatcher died)."""

    http_status = 504
    shed_reason = "deadline"


class ModelNotFoundError(ServingError):
    """No such model registered (or no active version)."""

    http_status = 404


class Request:
    """One enqueued predict: features plus everything needed to batch,
    deadline-check, and deliver it."""

    __slots__ = ("features", "rows", "model", "enqueued", "enqueued_ns",
                 "deadline", "done", "result", "cancelled", "orig_seq",
                 "trace_id", "queue_wait_ns", "execute_ns", "batch_rows")

    def __init__(self, features: np.ndarray, model: str,
                 deadline_s: float, orig_seq: Optional[int] = None,
                 trace_id: Optional[str] = None):
        self.features = features
        self.rows = len(features)
        self.model = model
        self.enqueued = time.monotonic()
        self.enqueued_ns = time.perf_counter_ns()  # span clock (tracing)
        self.deadline = self.enqueued + deadline_s
        self.done = threading.Event()
        self.result: list = []          # [np.ndarray] or [Exception]
        self.cancelled = False          # waiter gave up; skip, drop output
        self.orig_seq = orig_seq        # pre-seq-bucket length, for slicing
        self.trace_id = trace_id        # end-to-end request trace id
        # stage timings stamped by the batcher at dispatch (the O(1)
        # source request_breakdown/access-log read — the span ring is a
        # bounded diagnostic buffer, not the primary record)
        self.queue_wait_ns: Optional[int] = None
        self.execute_ns: Optional[int] = None
        self.batch_rows: Optional[int] = None

    def deliver(self, value) -> None:
        self.result.append(value)
        self.done.set()

    def expired(self, now: Optional[float] = None) -> bool:
        return (now if now is not None else time.monotonic()) > self.deadline


class AdmissionController:
    """Queue-budget + deadline policy (the batcher consults it under its
    own lock, so the controller itself is just arithmetic + metrics)."""

    def __init__(self, max_queue: int = 256, default_deadline_s: float = 30.0,
                 metrics=None):
        if max_queue < 1:
            raise ValueError(f"max_queue={max_queue} must be >= 1")
        if default_deadline_s <= 0:
            raise ValueError(
                f"default_deadline_s={default_deadline_s} must be > 0")
        self.max_queue = int(max_queue)
        self.default_deadline_s = float(default_deadline_s)
        self._metrics = metrics

    def shed(self, exc_type, detail: str = "",
             trace_id: Optional[str] = None):
        """Record the shed in the metrics registry and build the error;
        ``trace_id`` is stamped on the error (attribute AND message) so
        the rejection names the request it killed."""
        if self._metrics is not None and exc_type.shed_reason:
            self._metrics.shed.inc(reason=exc_type.shed_reason)
        if trace_id:
            detail = f"{detail} [trace {trace_id}]" if detail else (
                f"[trace {trace_id}]")
        err = exc_type(detail)
        err.trace_id = trace_id
        return err

    def check_admit(self, queued: int, stopping: bool,
                    trace_id: Optional[str] = None):
        """Raise the appropriate rejection for a new request, or return
        None to admit.  Called by the batcher with its lock held."""
        if stopping:
            raise self.shed(ShuttingDownError, "engine is shutting down",
                            trace_id=trace_id)
        if queued >= self.max_queue:
            raise self.shed(
                QueueFullError,
                f"queue budget exceeded ({queued} >= {self.max_queue})",
                trace_id=trace_id)

    def deadline_for(self, deadline_s: Optional[float]) -> float:
        d = self.default_deadline_s if deadline_s is None else float(deadline_s)
        if d <= 0:
            raise ValueError(f"deadline_s={d} must be > 0")
        return d
