"""Shape-keyed dynamic micro-batcher.

Fixes the two latency bugs of the PR-1 ``InferenceServer`` dispatch loop
and generalises it into the engine's core:

- **immediate dispatch**: the old loop unconditionally slept
  ``max_wait_ms`` before forming a batch, taxing every request even when
  a full batch was already queued.  Here a batch dispatches the moment
  its row budget saturates (or the head request is oversized); the wait
  only applies while a batch could still grow, and is measured from the
  OLDEST request's enqueue time.
- **O(1) queue ops**: pending requests live in ``collections.deque``
  per (model, row-shape) key — ``list.pop(0)`` was O(n) per request.

Keying by (model, row shape) means a batch is always concatenable and a
malformed request (wrong feature width) can only poison its own key,
never a well-formed neighbour's batch.  Expired-deadline requests are
dropped at the queue (their waiter gets ``DeadlineExceededError``)
without wasting a forward pass; on shutdown the loop either drains
(every queued request still served) or fails fast — either way no
waiter is left hanging.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Callable, Dict, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.observability.tracing import get_tracer
from deeplearning4j_tpu.serving.admission import (
    AdmissionController, DeadlineExceededError, Request, ShuttingDownError,
)

logger = logging.getLogger("deeplearning4j_tpu.serving")

_Key = Tuple[str, Tuple[int, ...]]


class DynamicBatcher:
    """One dispatch thread multiplexing all models/shapes of an engine.

    ``execute(model_name, feats)`` is the engine's bucket-padded forward
    pass; it runs OUTSIDE the queue lock so enqueues never block on the
    accelerator."""

    def __init__(self, execute: Callable[[str, np.ndarray], np.ndarray],
                 admission: AdmissionController, max_batch: int = 32,
                 max_wait_ms: float = 2.0, metrics=None):
        self._execute = execute
        self.admission = admission
        self.max_batch = int(max_batch)
        self.max_wait_s = float(max_wait_ms) / 1000.0
        self._metrics = metrics
        self._cv = threading.Condition()
        self._pending: Dict[_Key, deque] = {}
        self._queued = 0
        # lower bound on the earliest pending deadline: the full O(queued)
        # purge scan only runs when it can actually expire something, so a
        # deep backlog drains in O(n) dispatches, not O(n) scans per
        # dispatch
        self._earliest_deadline = float("inf")
        self._stop = False
        self._drain = True
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ client side
    @property
    def queued(self) -> int:
        # dl4jlint: disable-next-line=lock-discipline -- lock-free gauge read: GIL-atomic int, bound into dl4j_serving_queue_depth; must never contend with submit/dispatch
        return self._queued

    def queued_for(self, model: str) -> int:
        """Pending (not yet dispatched) requests targeting one model name
        — the canary teardown waits on this before the name leaves the
        registry, so no queued request can fail its lease."""
        with self._cv:
            return sum(len(dq) for key, dq in self._pending.items()
                       if key[0] == model)

    def is_alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def submit(self, req: Request) -> None:
        """Admit + enqueue (raises QueueFullError / ShuttingDownError)."""
        key = (req.model, tuple(req.features.shape[1:]))
        with self._cv:
            self.admission.check_admit(self._queued, self._stop,
                                       trace_id=req.trace_id)
            self._pending.setdefault(key, deque()).append(req)
            self._queued += 1
            if req.deadline < self._earliest_deadline:
                self._earliest_deadline = req.deadline
            self._cv.notify_all()

    # --------------------------------------------------------------- lifecycle
    def start(self) -> None:
        if self.is_alive():
            # e.g. a previous stop() timed out on a stuck execute: the old
            # loop still owns the queue — a second loop must never race it
            raise RuntimeError("dispatch thread is still running; "
                               "stop() it (and let it finish) first")
        with self._cv:
            self._stop = False
            self._drain = True
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="dl4j-serving-dispatch")
        self._thread.start()

    def stop(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Stop accepting work; ``drain=True`` serves everything already
        queued first, ``drain=False`` fails queued waiters immediately."""
        with self._cv:
            self._stop = True
            self._drain = drain
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout)
            if self._thread.is_alive():
                # the loop still owns the queue (e.g. stuck in a long XLA
                # compile): failing its waiters here would race its own
                # deliveries, and is_alive() must keep reporting the truth
                logger.warning("serving dispatch thread did not exit "
                               "within %.1fs; leaving it to finish",
                               timeout)
                return
            self._thread = None
        # belt-and-braces: if the thread was never started (or died), the
        # queue may still hold waiters — fail them rather than hang them
        self._fail_all_locked_safe()

    # ------------------------------------------------------------ loop innards
    def _fail_all_locked_safe(self) -> None:
        with self._cv:
            self._fail_all()

    def _fail_all(self) -> None:
        """Deliver shutdown errors to every queued waiter (lock held)."""
        for dq in self._pending.values():
            for req in dq:
                if not req.cancelled:
                    req.deliver(self.admission.shed(
                        ShuttingDownError, "engine stopped before dispatch",
                        trace_id=req.trace_id))
        self._pending.clear()
        self._queued = 0

    def _purge(self, now: float) -> None:
        """Drop cancelled/expired requests from every deque (lock held).
        Expired waiters get DeadlineExceededError without costing a
        forward pass.  Skipped entirely (O(1)) while no pending deadline
        can have passed; a full scan recomputes the exact next one."""
        if now < self._earliest_deadline:
            return
        earliest = float("inf")
        for key in list(self._pending):
            dq = self._pending[key]
            kept = None
            for req in dq:
                if req.cancelled:
                    self._queued -= 1
                elif req.expired(now):
                    req.deliver(self.admission.shed(
                        DeadlineExceededError,
                        f"deadline passed after "
                        f"{now - req.enqueued:.3f}s in queue",
                        trace_id=req.trace_id))
                    self._queued -= 1
                else:
                    if kept is None:
                        kept = deque()
                    kept.append(req)
                    if req.deadline < earliest:
                        earliest = req.deadline
            if kept is None:
                del self._pending[key]
            elif len(kept) != len(dq):
                self._pending[key] = kept
        self._earliest_deadline = earliest

    def _saturated(self, dq: deque) -> bool:
        """True when the takeable prefix cannot grow: the head alone
        overflows the budget, the budget is exactly met, or the next
        request would overflow it."""
        rows = 0
        for req in dq:
            if rows == 0 and req.rows >= self.max_batch:
                return True
            if rows + req.rows > self.max_batch:
                return True
            rows += req.rows
            if rows == self.max_batch:
                return True
        return False

    def _pick(self, now: float) -> Tuple[Optional[_Key], Optional[float]]:
        """(key ready to dispatch now, earliest future wakeup time).
        Readiness: stopping (drain fast), saturated budget, or oldest
        request aged past max_wait.  Among ready keys the OLDEST head
        wins — first-ready-in-dict-order would let one continuously
        saturated key starve every other key's traffic.  Lock held."""
        wake = None
        ready, ready_head = None, None
        for key, dq in self._pending.items():
            if not dq:
                continue
            head = dq[0].enqueued
            head_ready_at = head + self.max_wait_s
            if self._stop or now >= head_ready_at or self._saturated(dq):
                if ready is None or head < ready_head:
                    ready, ready_head = key, head
                continue
            t = min(head_ready_at, dq[0].deadline)
            wake = t if wake is None else min(wake, t)
        return ready, None if ready is not None else wake

    def _take(self, key: _Key) -> list:
        """Pop the dispatchable prefix: requests until the row budget
        fills (a single oversized request is taken alone — the engine
        chunks it through the bucket set).  Lock held."""
        dq = self._pending[key]
        batch, rows = [], 0
        while dq and (not batch or rows + dq[0].rows <= self.max_batch):
            req = dq.popleft()
            self._queued -= 1
            if req.cancelled:
                continue
            batch.append(req)
            rows += req.rows
        if not dq:
            del self._pending[key]
        return batch

    def _loop(self) -> None:
        while True:
            batch = None
            with self._cv:
                while batch is None:
                    now = time.monotonic()
                    self._purge(now)
                    if self._stop and (not self._drain or self._queued == 0):
                        if not self._drain:
                            self._fail_all()
                        return
                    key, wake = self._pick(now)
                    if key is not None:
                        batch = self._take(key)
                        if not batch:      # all cancelled; re-evaluate
                            batch = None
                        continue
                    # also wake for the earliest pending deadline, which
                    # may sit mid-deque where _pick's head scan missed it
                    if self._earliest_deadline != float("inf"):
                        wake = (self._earliest_deadline if wake is None
                                else min(wake, self._earliest_deadline))
                    self._cv.wait(None if wake is None
                                  else max(0.0, wake - now))
            self._dispatch(batch)

    def _dispatch(self, batch: list) -> None:
        now = time.monotonic()
        now_ns = time.perf_counter_ns()
        tracer = get_tracer()
        for req in batch:
            req.queue_wait_ns = now_ns - req.enqueued_ns
            if self._metrics is not None:
                self._metrics.queue_wait.observe(now - req.enqueued,
                                                 exemplar=req.trace_id)
            if req.trace_id:
                # per-request queue stage: enqueue -> batch dispatch
                tracer.record_span("serving_queue_wait", req.enqueued_ns,
                                   now_ns, trace_id=req.trace_id,
                                   model=req.model, rows=req.rows)
        feats = (batch[0].features if len(batch) == 1
                 else np.concatenate([r.features for r in batch]))
        if self._metrics is not None:
            self._metrics.batch_rows.observe(len(feats))
        err = None
        t_ex0 = time.perf_counter_ns()
        try:
            out = self._execute(batch[0].model, feats)
        except Exception as e:  # deliver to waiters; the loop must survive
            err = e
        t_ex1 = time.perf_counter_ns()
        pos = 0
        for req in batch:
            req.execute_ns = t_ex1 - t_ex0
            req.batch_rows = len(feats)
            if req.trace_id:
                # the execute stage is shared by the whole micro-batch;
                # each request gets its own span so a trace-id query
                # returns the full queue/execute breakdown
                tracer.record_span(
                    "serving_execute", t_ex0, t_ex1, trace_id=req.trace_id,
                    model=req.model, rows=req.rows, batch_rows=len(feats),
                    **({"error": repr(err)} if err is not None else {}))
            if err is not None:
                req.deliver(err)
            else:
                req.deliver(out[pos:pos + req.rows])
                pos += req.rows
