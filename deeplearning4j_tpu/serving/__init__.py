"""Production serving subsystem: shape-bucketed dynamic batching, AOT
warmup, model registry with zero-drop hot-swap, and admission control.

The HTTP ``streaming.InferenceServer`` and the broker-based
``streaming.ServingPipeline`` are thin front-ends over the
``ServingEngine`` defined here.  See docs/serving.md.
"""

from deeplearning4j_tpu.serving.admission import (
    AdmissionController, DeadlineExceededError, ModelNotFoundError,
    QueueFullError, Request, ServingError, ShuttingDownError,
)
from deeplearning4j_tpu.serving.batcher import DynamicBatcher
from deeplearning4j_tpu.serving.buckets import BucketPolicy
from deeplearning4j_tpu.serving.engine import DEFAULT_MODEL, ServingEngine
from deeplearning4j_tpu.serving.registry import (
    ModelRegistry, ModelVersion, load_version_from_checkpoint,
)
from deeplearning4j_tpu.serving.warmup import (
    NoWarmupShapeError, infer_row_shape, warmup_version,
)

__all__ = [
    "AdmissionController", "BucketPolicy", "DEFAULT_MODEL",
    "DeadlineExceededError", "DynamicBatcher", "ModelNotFoundError",
    "ModelRegistry", "ModelVersion", "NoWarmupShapeError",
    "QueueFullError", "Request", "ServingEngine", "ServingError",
    "ShuttingDownError", "infer_row_shape", "load_version_from_checkpoint",
    "warmup_version",
]
