"""Named/versioned model registry with atomic hot-swap.

The reference redeploys a serving route by restarting it; here a new
checkpoint is loaded and WARMED while the old version keeps serving,
then the active pointer flips atomically.  Requests never reference a
version until the moment their batch executes (the batcher takes a
lease), so a swap drops zero requests: batches in flight on the old
version run to completion under their lease, every later batch sees the
new version, and ``retire`` blocks until the old version's in-flight
count drains to zero before it is marked retired.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Dict, List, Optional, Tuple

from deeplearning4j_tpu.observability.recompile import RecompileDetector
from deeplearning4j_tpu.serving.admission import ModelNotFoundError

ACTIVE = "active"
PENDING = "pending"    # loaded + warming, not yet serving
RETAINED = "retained"  # displaced by a swap, kept loaded for rollback
RETIRED = "retired"


class ModelVersion:
    """One immutable (model object, version) pair plus its serving state.

    Each version owns its own ``RecompileDetector`` (named
    ``serving.<model>``): a fresh version has a fresh jit cache, so its
    warmup compiles are real compiles and must be counted."""

    def __init__(self, name: str, version: int, model,
                 example=None, metrics_registry=None):
        self.name = name
        self.version = int(version)
        self.model = model
        self.model_type = type(model).__name__   # survives model release
        self.example = example          # single-row ndarray for warmup
        self.state = PENDING
        self.created = time.time()
        self.inflight = 0               # batches currently executing
        self.detector = RecompileDetector(
            f"serving.{name}", registry=metrics_registry)

    @property
    def key(self) -> str:
        return f"{self.name}@v{self.version}"

    def as_dict(self) -> dict:
        return {"name": self.name, "version": self.version,
                "state": self.state, "inflight": self.inflight,
                "model_type": self.model_type,
                "compiled_signatures": self.detector.compile_count}


class ModelRegistry:
    """Thread-safe name -> active ModelVersion map (plus retired history).

    Retiring a version RELEASES its model reference (the weights are the
    memory cost; the history keeps only metadata) and the history itself
    is capped — a server hot-swapping for months must not leak one model
    per swap."""

    HISTORY_LIMIT = 16

    def __init__(self, metrics_registry=None):
        self._cv = threading.Condition()
        self._active: Dict[str, ModelVersion] = {}
        self._previous: Dict[str, ModelVersion] = {}   # rollback targets
        self._history: List[ModelVersion] = []
        self._next_version: Dict[str, int] = {}
        self._metrics_registry = metrics_registry

    # ------------------------------------------------------------ mutation
    def new_version(self, name: str, model, example=None,
                    version: Optional[int] = None) -> ModelVersion:
        """Build (but do not activate) the next version of ``name`` —
        the engine warms it up before calling ``activate``."""
        with self._cv:
            v = (self._next_version.get(name, 1)
                 if version is None else int(version))
            # a pinned (manifest) version must never rewind the counter,
            # or a later auto-assigned version would duplicate an old one
            self._next_version[name] = max(
                self._next_version.get(name, 1), v + 1)
            return ModelVersion(name, v, model, example,
                                self._metrics_registry)

    def activate(self, mv: ModelVersion,
                 retain: bool = False) -> Optional[ModelVersion]:
        """Atomically make ``mv`` the active version of its name;
        returns the displaced version (still counted in-flight by any
        executing batches) or None.

        With ``retain`` the displaced version is NOT moved to the retired
        history: it keeps its model loaded in state ``retained`` and
        becomes the ``rollback`` target — the post-swap watch window's
        undo button.  Callers that retain must eventually resolve the
        pair: ``rollback(name)`` to flip back, or ``release_retained``
        (then ``retire``) once the watch window closes cleanly.  An
        earlier retained version still unresolved when a new swap lands
        is returned to the history (model intact — the caller retires it
        to release the weights)."""
        with self._cv:
            old = self._active.get(mv.name)
            stale_retained = self._previous.pop(mv.name, None)
            mv.state = ACTIVE
            self._active[mv.name] = mv
            if old is not None:
                if retain:
                    old.state = RETAINED
                    self._previous[mv.name] = old
                else:
                    self._history.append(old)
            if stale_retained is not None and stale_retained is not old:
                self._history.append(stale_retained)
            del self._history[:-self.HISTORY_LIMIT]
            self._cv.notify_all()
            return old

    def rollback(self, name: str) -> "Tuple[ModelVersion, ModelVersion]":
        """Atomically flip the active pointer of ``name`` back to the
        version retained by the last ``activate(..., retain=True)``.
        Returns ``(restored, displaced)``: the restored previous version
        (now active again) and the displaced bad version — still serving
        its in-flight leased batches, so the caller ``retire``s it after
        the flip to drain and release it.  Raises ``ModelNotFoundError``
        when nothing is retained (rollback window already closed or no
        retaining swap happened).

        Like ``activate`` this is one atomic pointer flip under the
        registry lock: a request leasing concurrently gets either the bad
        version (its batch completes under the lease) or the restored
        one — never an error, never a dropped request."""
        with self._cv:
            prev = self._previous.pop(name, None)
            if prev is None:
                raise ModelNotFoundError(
                    f"no retained previous version of {name!r} to roll "
                    f"back to")
            displaced = self._active.get(name)
            prev.state = ACTIVE
            self._active[name] = prev
            if displaced is not None:
                self._history.append(displaced)
                del self._history[:-self.HISTORY_LIMIT]
            self._cv.notify_all()
            return prev, displaced

    def retained(self, name: str) -> Optional[ModelVersion]:
        with self._cv:
            return self._previous.get(name)

    def release_retained(self, name: str) -> Optional[ModelVersion]:
        """Close the rollback window: pop the retained previous version
        (watch window passed cleanly) and move it to the history.  The
        caller ``retire``s the returned version to drain its in-flight
        batches and release the model reference; returns None when
        nothing is retained."""
        with self._cv:
            mv = self._previous.pop(name, None)
            if mv is not None:
                self._history.append(mv)
                del self._history[:-self.HISTORY_LIMIT]
            return mv

    def remove(self, name: str) -> Optional[ModelVersion]:
        """Drop ``name`` from the active map entirely (canary teardown —
        the route name stops existing rather than being replaced).
        Returns the removed version, moved to the history with its model
        intact; the caller ``retire``s it to drain in-flight batches and
        release the weights.  None when the name was never registered."""
        with self._cv:
            mv = self._active.pop(name, None)
            stale = self._previous.pop(name, None)
            for m in (mv, stale):
                if m is not None:
                    self._history.append(m)
            del self._history[:-self.HISTORY_LIMIT]
            self._cv.notify_all()
            return mv

    def register(self, name: str, model, example=None,
                 version: Optional[int] = None) -> ModelVersion:
        """Shorthand: new version activated immediately (startup path —
        hot-swaps go through the engine so they warm up first)."""
        mv = self.new_version(name, model, example, version)
        self.activate(mv)
        return mv

    def retire(self, mv: ModelVersion, timeout: float = 30.0) -> bool:
        """Wait for ``mv``'s in-flight batches to drain, then mark it
        retired and release its model reference (weights freed; history
        keeps the metadata).  Returns False if the drain timed out
        (version left un-retired with its model intact; callers may
        retry)."""
        deadline = time.monotonic() + timeout
        with self._cv:
            while mv.inflight > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cv.wait(remaining)
            mv.state = RETIRED
            mv.model = None
            return True

    # ------------------------------------------------------------- reading
    def active(self, name: str) -> ModelVersion:
        with self._cv:
            mv = self._active.get(name)
            if mv is None:
                raise ModelNotFoundError(
                    f"no model registered under {name!r} "
                    f"(have: {sorted(self._active)})")
            return mv

    def names(self) -> List[str]:
        with self._cv:
            return sorted(self._active)

    def as_dict(self) -> dict:
        with self._cv:
            return {
                "active": {n: mv.as_dict()
                           for n, mv in self._active.items()},
                "retained": {n: mv.as_dict()
                             for n, mv in self._previous.items()},
                "retired": [mv.as_dict() for mv in self._history],
            }

    # -------------------------------------------------------------- leases
    @contextlib.contextmanager
    def lease(self, name: str):
        """Pin the CURRENT active version for the duration of one batch
        execution.  The swap path never blocks on leases — it only waits
        in ``retire`` for them to drain."""
        with self._cv:
            mv = self._active.get(name)
            if mv is None:
                raise ModelNotFoundError(
                    f"no model registered under {name!r} "
                    f"(have: {sorted(self._active)})")
            mv.inflight += 1
        try:
            yield mv
        finally:
            with self._cv:
                mv.inflight -= 1
                self._cv.notify_all()


def load_version_from_checkpoint(registry: ModelRegistry, name: str, path,
                                 example=None) -> ModelVersion:
    """Build a PENDING version from a ``models/serialization.py``
    checkpoint zip.  A ``serving_version`` entry in the checkpoint
    manifest (see ``write_model(extra_manifest=...)``) pins the version
    number; otherwise the registry's per-name counter assigns one."""
    from deeplearning4j_tpu.models import serialization

    model = serialization.load_model(path, load_updater=False)
    version = serialization.read_manifest(path).get("serving_version")
    return registry.new_version(name, model, example=example,
                                version=version)
