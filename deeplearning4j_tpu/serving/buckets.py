"""Shape bucketing policy for the serving engine.

XLA compiles one executable per abstract input shape, so a serving path
that forwards raw ragged request batches retraces constantly — and one
that pads everything to a single ``max_batch`` (the old
``InferenceServer`` behaviour) makes a 1-row request pay the FLOPs and
HBM traffic of a full tile.  The middle ground is a small CLOSED set of
shapes: batch-size buckets in powers of two up to ``max_batch`` (and,
for recurrent/attention models, optional sequence-length buckets on the
time axis).  Every dispatched forward pass is padded UP to the nearest
bucket, so

- a request never pays more than 2x its own padding FLOPs, and
- the compiler only ever sees ``len(buckets)`` (x ``len(seq_buckets)``)
  signatures, all of which AOT warmup can precompile at startup.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple


def _pow2_buckets(max_value: int) -> Tuple[int, ...]:
    """1, 2, 4, … up to ``max_value`` (``max_value`` always included, so a
    non-power-of-two cap still gets a full-budget bucket)."""
    out = []
    b = 1
    while b < max_value:
        out.append(b)
        b *= 2
    out.append(max_value)
    return tuple(out)


class BucketPolicy:
    """The closed shape set the engine is allowed to hand the compiler.

    ``batch_buckets`` — allowed row counts, ascending; defaults to powers
    of two up to ``max_batch``.  Passing ``batch_buckets=(max_batch,)``
    reproduces the legacy fixed-shape path (everything padded to one
    size) — the serving bench uses exactly that as its comparison arm.

    ``seq_buckets`` — optional allowed lengths for the TIME axis (axis 1
    of a rank>=3 input).  Inputs are zero-padded up to the nearest
    bucket; callers serving recurrent models whose semantics depend on
    exact sequence length should pass feature masks or disable this.
    """

    def __init__(self, max_batch: int = 32,
                 batch_buckets: Optional[Sequence[int]] = None,
                 seq_buckets: Optional[Sequence[int]] = None):
        if max_batch < 1:
            raise ValueError(f"max_batch={max_batch} must be >= 1")
        self.max_batch = int(max_batch)
        if batch_buckets is None:
            self.batch_buckets = _pow2_buckets(self.max_batch)
        else:
            bb = tuple(sorted(int(b) for b in batch_buckets))
            if not bb or bb[0] < 1:
                raise ValueError(f"bad batch_buckets {batch_buckets}")
            if bb[-1] != self.max_batch:
                raise ValueError(
                    f"largest batch bucket {bb[-1]} must equal "
                    f"max_batch {self.max_batch}")
            self.batch_buckets = bb
        self.seq_buckets = (None if seq_buckets is None
                            else tuple(sorted(int(s) for s in seq_buckets)))

    # ------------------------------------------------------------- lookups
    def bucket_rows(self, rows: int) -> int:
        """Smallest batch bucket >= rows (rows above ``max_batch`` are the
        batcher's problem — it chunks before asking)."""
        for b in self.batch_buckets:
            if rows <= b:
                return b
        return self.batch_buckets[-1]

    def bucket_seq(self, length: int) -> int:
        """Smallest sequence bucket >= length; lengths beyond the largest
        bucket pass through unpadded (one extra signature, no truncation)."""
        if self.seq_buckets is None:
            return length
        for s in self.seq_buckets:
            if length <= s:
                return s
        return length

    # -------------------------------------------------------------- warmup
    def warmup_shapes(self, row_shape: Sequence[int]) -> list:
        """Every full input shape AOT warmup must precompile for a model
        whose single example row has shape ``row_shape`` (no batch dim).
        With seq buckets a rank>=2 row's leading (time) axis is swept
        over every bucket; a rank-1 (dense) row has no time axis — the
        same rule ``predict`` applies (it only seq-buckets rank>=3
        inputs), so warmup and serve-time shape sets always match."""
        row_shape = tuple(int(d) for d in row_shape)
        shapes = []
        if self.seq_buckets is not None and len(row_shape) >= 2:
            for s in self.seq_buckets:
                for b in self.batch_buckets:
                    shapes.append((b, s) + row_shape[1:])
        else:
            for b in self.batch_buckets:
                shapes.append((b,) + row_shape)
        return shapes

    def __repr__(self):
        return (f"BucketPolicy(batch={list(self.batch_buckets)}, "
                f"seq={list(self.seq_buckets) if self.seq_buckets else None})")
