"""AOT warmup: precompile every bucket shape before a version serves.

XLA compilation costs seconds against a sub-millisecond forward pass; a
compile triggered by live traffic is a multi-second p99.9 spike AND it
stalls every other request sharing the dispatch thread.  Because the
bucket policy closes the shape set, the whole set can be compiled at
startup (and during a hot-swap, on the INCOMING version while the old
one still serves) — steady-state serving then triggers exactly zero
compiles, which ``dl4j_compiles_total{fn="serving.<name>"}`` proves.

Each warmup shape is driven through the version's RecompileDetector with
the SAME fingerprint the engine uses at serve time, so a serve-time
signature is new only if warmup never saw it.
"""

from __future__ import annotations

import contextlib
import logging
from typing import Optional, Sequence, Tuple

import numpy as np

logger = logging.getLogger("deeplearning4j_tpu.serving")


class NoWarmupShapeError(ValueError):
    """Warmup is impossible because no example row shape is known — the
    engine downgrades THIS to a warning (first traffic compiles on
    demand); any other warmup failure is a genuinely broken model and
    must abort the deploy instead of activating it."""


def infer_row_shape(model) -> Optional[Tuple[int, ...]]:
    """Best-effort single-row feature shape from the model config (dense
    first layer -> ``(n_in,)``); None when it cannot be derived (conv /
    graph inputs) — the caller must then provide an example row."""
    layers = getattr(model, "layers", None)
    if layers:
        n_in = getattr(layers[0], "n_in", None)
        if isinstance(n_in, int) and n_in > 0:
            return (n_in,)
    return None


def warmup_version(mv, policy, row_shape: Optional[Sequence[int]] = None,
                   dtype=np.float32, metrics=None) -> int:
    """Run one forward pass per bucket shape through ``mv``'s detector
    and model; returns the number of shapes compiled.  Raises
    ``NoWarmupShapeError`` when no row shape is known (explicit beats a
    silently cold cache); model failures propagate as-is."""
    if row_shape is None:
        row_shape = (tuple(mv.example.shape) if mv.example is not None
                     else infer_row_shape(mv.model))
    if row_shape is None:
        raise NoWarmupShapeError(
            f"cannot warm up {mv.key}: no example row provided and the "
            f"input shape is not derivable from the model config — pass "
            f"example= when registering/deploying the model")
    shapes = policy.warmup_shapes(row_shape)
    timer = (metrics.warmup_seconds.time() if metrics is not None
             else contextlib.nullcontext())
    with timer:
        for shape in shapes:
            x = np.zeros(shape, dtype)
            mv.detector.check((x,), {}, expected=True)
            np.asarray(mv.model.output(x))
    if metrics is not None:
        metrics.warmup_shapes.set(len(shapes), model=mv.name)
    logger.info("warmed %s: %d bucket shapes precompiled (%s)",
                mv.key, len(shapes), [s[0] for s in shapes])
    return len(shapes)
