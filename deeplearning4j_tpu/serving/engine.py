"""ServingEngine: bucketed batching + AOT warmup + hot-swap + admission.

The production serving core the HTTP ``InferenceServer`` and the
broker-based ``ServingPipeline`` are thin front-ends over.  One engine
owns:

- a ``BucketPolicy`` (the closed shape set XLA may see),
- a ``ModelRegistry`` (named/versioned models, atomic hot-swap),
- an ``AdmissionController`` (queue budget, deadlines, shedding),
- a ``DynamicBatcher`` (one dispatch thread multiplexing all models),
- a ``ServingMetrics`` bundle (Prometheus-convention families).

Request path: ``predict`` normalises features, stamps a deadline,
submits through admission, and waits BOUNDED on the result — a dead
dispatcher or an overloaded queue surfaces as a typed error, never a
hang.  Batches resolve their model version only at execution time (a
registry lease), which is what makes ``deploy`` a zero-drop swap: warm
the incoming version while the old one serves, flip atomically, let the
old version's in-flight batches drain, retire it.
"""

from __future__ import annotations

import logging
import os
import random
import threading
import time
import weakref
from collections import OrderedDict
from typing import Optional

import numpy as np

from deeplearning4j_tpu.observability.flightrecorder import (
    get_flight_recorder, step_guard,
)
from deeplearning4j_tpu.observability.servingmetrics import ServingMetrics
from deeplearning4j_tpu.observability.tracing import get_tracer, new_trace_id
from deeplearning4j_tpu.serving.admission import (
    AdmissionController, DeadlineExceededError, ModelNotFoundError,
    QueueFullError, Request, ServingError, ShuttingDownError,
)
from deeplearning4j_tpu.serving.batcher import DynamicBatcher
from deeplearning4j_tpu.serving.buckets import BucketPolicy
from deeplearning4j_tpu.serving.registry import (
    ModelRegistry, ModelVersion, load_version_from_checkpoint,
)
from deeplearning4j_tpu.serving.warmup import (
    NoWarmupShapeError, warmup_version,
)

logger = logging.getLogger("deeplearning4j_tpu.serving")

DEFAULT_MODEL = "default"


class _CanaryRoute:
    """Traffic split for one model name: requests for the primary are
    rerouted to the canary version with probability ``fraction`` (seeded
    RNG — tests and replays see the same routing sequence), and every
    rerouted request's outcome is tallied.  The promotion watch decides
    promote-vs-reject on these counts, so sheds are tracked separately:
    a full queue is the engine's state, not the canary's fault, while
    errors and deadline expiries on canary traffic are exactly the
    regressions a canary exists to absorb before a full swap would."""

    def __init__(self, canary_model: str, fraction: float, seed: int = 0):
        self.canary_model = canary_model
        self.fraction = float(fraction)
        self.started = time.time()
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self.counts = {"ok": 0, "error": 0, "deadline": 0, "shed": 0}

    def take(self) -> bool:
        if self.fraction >= 1.0:
            return True
        if self.fraction <= 0.0:
            return False
        with self._lock:
            return self._rng.random() < self.fraction

    def record(self, status: str) -> None:
        with self._lock:
            if status not in self.counts:
                status = ("shed" if status in ("queue_full", "shutdown")
                          else "error")
            self.counts[status] += 1

    def as_dict(self) -> dict:
        with self._lock:
            counts = dict(self.counts)
        total = sum(counts.values())
        # sheds are visible but JUDGE nothing: a full queue is the
        # engine's load, not the canary's regression — the evidence
        # threshold and the error rate are over requests that actually
        # reached (or should have reached) the model
        judged = counts["ok"] + counts["error"] + counts["deadline"]
        bad = counts["error"] + counts["deadline"]
        return {"canary_model": self.canary_model,
                "fraction": self.fraction,
                "requests": total, "judged": judged, "bad": bad,
                "error_rate": (bad / judged) if judged else 0.0,
                **counts}


class ServingEngine:
    """See module docstring.  Minimal use::

        engine = ServingEngine(model, max_batch=32,
                               example=np.zeros((n_in,), np.float32))
        engine.start()            # AOT-warms every bucket shape
        out = engine.predict(x)   # thread-safe, batched, deadline-bounded
        engine.deploy("default", new_model)   # zero-drop hot-swap
        engine.stop()             # graceful drain
    """

    def __init__(self, model=None, *, max_batch: int = 32,
                 max_wait_ms: float = 2.0, max_queue: int = 256,
                 deadline_s: float = 30.0, policy: Optional[BucketPolicy] = None,
                 models: Optional[ModelRegistry] = None, registry=None,
                 example: Optional[np.ndarray] = None,
                 default_model: str = DEFAULT_MODEL):
        self.policy = policy or BucketPolicy(max_batch=max_batch)
        self.metrics = ServingMetrics(registry)
        self.metrics.set_max_batch(self.policy.max_batch)
        self.models = models or ModelRegistry(
            metrics_registry=self.metrics.registry)
        self.default_model = default_model
        if model is not None:
            self.models.register(default_model, model, example=example)
        self.admission = AdmissionController(
            max_queue=max_queue, default_deadline_s=deadline_s,
            metrics=self.metrics)
        self.batcher = DynamicBatcher(
            self._execute_batch, self.admission,
            max_batch=self.policy.max_batch, max_wait_ms=max_wait_ms,
            metrics=self.metrics)
        self._bind_queue_gauge()
        self._swap_lock = threading.Lock()
        # trace_id -> per-stage breakdown of recently completed requests
        # (bounded LRU; O(1) for the access log — the span ring is the
        # fallback for ids that have aged out of this cache)
        self._breakdowns: "OrderedDict[str, dict]" = OrderedDict()
        self._breakdown_lock = threading.Lock()
        self._breakdown_cap = 2048
        # name -> _CanaryRoute: a fraction of this model's traffic is
        # diverted to a candidate version (see start_canary).  Routing
        # lookups happen on every predict, so the map gets its own tiny
        # lock instead of riding _swap_lock (whose holders may be deep in
        # an XLA warmup); mutators hold BOTH: _swap_lock serialises the
        # canary lifecycle, _canary_lock makes each map op atomic against
        # the readers.  Lock order: _swap_lock outer, _canary_lock inner.
        self._canary: "dict[str, _CanaryRoute]" = {}
        self._canary_lock = threading.Lock()
        # per-model outcome tallies (see status_counts)
        self._model_status: "dict[str, dict[str, int]]" = {}

    def _bind_queue_gauge(self) -> None:
        # weakref: the registry outlives the engine — a strong closure
        # would pin the batcher (and through it the models) forever
        ref = weakref.ref(self.batcher)
        self.metrics.bind_queue_depth(
            lambda: b.queued if (b := ref()) is not None else 0.0)

    # ------------------------------------------------------------- lifecycle
    def start(self, warmup: bool = True) -> "ServingEngine":
        """Start the dispatcher; with ``warmup`` (default) precompile
        every bucket shape of every registered model so steady-state
        serving triggers zero XLA compiles.  A model whose input shape
        cannot be derived (and that has no example) is skipped with a
        warning — its first live shapes compile on demand instead; any
        OTHER warmup failure means a broken model and propagates."""
        if warmup:
            for name in self.models.names():
                mv = self.models.active(name)
                try:
                    warmup_version(mv, self.policy, metrics=self.metrics)
                except NoWarmupShapeError as e:
                    logger.warning("skipping warmup: %s", e)
        self.batcher.start()
        self._bind_queue_gauge()   # stop() freezes the gauge; re-arm it
        return self

    def stop(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Graceful shutdown: with ``drain`` every queued request is
        still served; without, queued waiters fail with 503 — in both
        cases no waiter is left hanging."""
        self.batcher.stop(drain=drain, timeout=timeout)
        self.metrics.freeze_queue_depth()

    # ---------------------------------------------------------------- predict
    def predict(self, features: np.ndarray, model: Optional[str] = None,
                deadline_s: Optional[float] = None,
                trace_id: Optional[str] = None) -> np.ndarray:
        """Thread-safe batched inference.  Raises ``QueueFullError``
        (shed), ``ShuttingDownError``, ``DeadlineExceededError``, or the
        model's own failure — bounded by the request deadline either
        way.

        ``trace_id`` (minted here when absent) rides the request end to
        end: queue and execute stages record spans stamped with it
        (``SpanTracer.spans_for_trace``), shed/deadline errors carry it
        (``.trace_id`` attribute + message), shed flight events name it,
        and it is sampled as the exemplar onto the latency histogram."""
        trace_id = trace_id or new_trace_id()
        primary = model = model or self.default_model
        with self._canary_lock:
            route = self._canary.get(model)
        if route is not None and route.take():
            model = route.canary_model
        else:
            route = None
        feats = np.asarray(features, np.float32)
        if feats.ndim == 1:
            feats = feats[None, :]
        if len(feats) == 0:
            raise ValueError("predict called with zero rows")
        orig_seq = None
        if self.policy.seq_buckets is not None and feats.ndim >= 3:
            orig_seq = feats.shape[1]
            target = self.policy.bucket_seq(orig_seq)
            if target > orig_seq:
                pad = np.zeros(
                    (feats.shape[0], target - orig_seq) + feats.shape[2:],
                    feats.dtype)
                feats = np.concatenate([feats, pad], axis=1)
        deadline = self.admission.deadline_for(deadline_s)
        req = Request(feats, model, deadline, orig_seq, trace_id=trace_id)
        t0 = time.perf_counter()
        t0_ns = time.perf_counter_ns()
        status = "error"
        try:
            try:
                res = self._predict_wait(req, model, deadline, trace_id, t0,
                                         quiet_model_missing=route is not None)
            except ModelNotFoundError:
                if route is None:
                    raise
                # the canary was torn down between routing and dispatch
                # (stop_canary's queue drain cannot see a request that
                # passed take() but hasn't submitted yet) — the zero-drop
                # contract outranks the split: fall back to the primary
                model = primary
                req = Request(feats, model, deadline, orig_seq,
                              trace_id=trace_id)
                res = self._predict_wait(req, model, deadline, trace_id, t0)
            status = "ok"
            if (orig_seq is not None and res.ndim >= 3
                    and res.shape[1] > orig_seq):
                res = res[:, :orig_seq]   # trim time-distributed pad steps
            return res
        except ServingError as e:
            status = e.shed_reason or "error"
            raise
        finally:
            t1_ns = time.perf_counter_ns()
            get_tracer().record_span(
                "serving_request", t0_ns, t1_ns,
                trace_id=trace_id, model=model, rows=req.rows,
                status=status)
            self._remember_breakdown(req, trace_id, status,
                                     (t1_ns - t0_ns) / 1e6)
            if route is not None:
                route.record(status)

    def _predict_wait(self, req: Request, model: str, deadline: float,
                      trace_id: str, t0: float,
                      quiet_model_missing: bool = False) -> np.ndarray:
        """Submit + bounded wait + result classification (the predict
        body; split so ``predict`` can bracket it with the request
        span).  ``quiet_model_missing`` (the canary-routed attempt): a
        ``ModelNotFoundError`` result re-raises WITHOUT counters or
        flight events — the caller retries on the primary, and one
        client call must not show up as a phantom error next to its own
        success in the metrics the SLO rules read."""
        try:
            self.batcher.submit(req)
        except ServingError as e:
            self.metrics.requests.inc(status="shed")
            get_flight_recorder().record("shed", model=model,
                                         reason=type(e).__name__,
                                         trace_id=trace_id)
            raise
        # +grace so the queue-side deadline purge (which produces the more
        # informative error and owns shed{reason="deadline"}) normally
        # wins the race against this waiter
        if not req.done.wait(max(0.0, req.deadline - time.monotonic()) + 0.5):
            req.cancelled = True
            # the purge may have delivered between the timeout and here —
            # prefer its result so the shed counter is bumped exactly once
            if not req.done.is_set():
                self.metrics.requests.inc(status="deadline")
                get_flight_recorder().record("shed", model=model,
                                             reason="deadline",
                                             trace_id=trace_id)
                err = DeadlineExceededError(
                    f"no result within {deadline:.3f}s deadline "
                    f"(dispatcher dead or engine overloaded) "
                    f"[trace {trace_id}]")
                err.trace_id = trace_id
                raise err
        res = req.result[0]
        if quiet_model_missing and isinstance(res, ModelNotFoundError):
            raise res    # primary retry owns this request's accounting
        self.metrics.latency.observe(time.perf_counter() - t0,
                                     exemplar=trace_id)
        self.metrics.request_rows.observe(req.rows)
        if isinstance(res, Exception):
            if isinstance(res, DeadlineExceededError):
                self.metrics.requests.inc(status="deadline")
            elif isinstance(res, (QueueFullError, ShuttingDownError)):
                self.metrics.requests.inc(status="shed")
            else:
                self.metrics.requests.inc(status="error")
            if isinstance(res, ServingError):
                get_flight_recorder().record("shed", model=model,
                                             reason=type(res).__name__,
                                             trace_id=trace_id)
            raise res
        self.metrics.requests.inc(status="ok")
        return res

    def _remember_breakdown(self, req: Request, trace_id: str, status: str,
                            total_ms: float) -> None:
        """Cache the completed request's per-stage timings (stamped on
        the Request by the batcher) under its trace id — O(1) for the
        access log, immune to span-ring eviction.  Also tallies the
        outcome under the request's MODEL name (``status_counts``): the
        shared ``dl4j_serving_requests_total`` counter has no model
        label, and the promotion watch must not attribute another
        model's errors to a freshly swapped candidate."""
        entry = {
            "trace_id": trace_id,
            "queue_wait_ms": (None if req.queue_wait_ns is None
                              else req.queue_wait_ns / 1e6),
            "execute_ms": (None if req.execute_ns is None
                           else req.execute_ns / 1e6),
            "total_ms": total_ms,
            "status": status,
            "batch_rows": req.batch_rows,
            "bucket": (None if not req.batch_rows else self.policy.
                       bucket_rows(min(int(req.batch_rows),
                                       self.policy.max_batch))),
        }
        with self._breakdown_lock:
            self._breakdowns[trace_id] = entry
            self._breakdowns.move_to_end(trace_id)
            while len(self._breakdowns) > self._breakdown_cap:
                self._breakdowns.popitem(last=False)
            tally = self._model_status.setdefault(req.model, {})
            tally[status] = tally.get(status, 0) + 1

    def status_counts(self, model: str) -> dict:
        """Cumulative request outcomes for ONE model name (``ok`` /
        ``error`` / ``deadline`` / ``queue_full`` / ``shutdown``) — the
        per-model view the promotion watch diffs across its window."""
        with self._breakdown_lock:
            return dict(self._model_status.get(model, {}))

    def request_breakdown(self, trace_id: str) -> dict:
        """Per-stage timing of one traced request: queue wait, execute
        time, and the bucket its batch dispatched at (None for stages
        that never ran — e.g. a shed request has no execute stage).
        Served O(1) from the completed-request cache; falls back to a
        span-ring scan for ids that aged out of it."""
        with self._breakdown_lock:
            hit = self._breakdowns.get(trace_id)
            if hit is not None:
                return dict(hit)
        out = {"trace_id": trace_id, "queue_wait_ms": None,
               "execute_ms": None, "total_ms": None, "status": None,
               "batch_rows": None, "bucket": None}
        for s in get_tracer().spans_for_trace(trace_id):
            if s.name == "serving_queue_wait":
                out["queue_wait_ms"] = s.duration_ms
            elif s.name == "serving_execute":
                out["execute_ms"] = s.duration_ms
                rows = s.attrs.get("batch_rows")
                out["batch_rows"] = rows
                if rows:
                    out["bucket"] = self.policy.bucket_rows(
                        min(int(rows), self.policy.max_batch))
            elif s.name == "serving_request":
                out["total_ms"] = s.duration_ms
                out["status"] = s.attrs.get("status")
        return out

    # ----------------------------------------------------------- model admin
    def deploy(self, name: str, model_or_path, *, example=None,
               version: Optional[int] = None, warmup: bool = True,
               retain_old: bool = False,
               drain_timeout: float = 30.0) -> ModelVersion:
        """Register a model (or load a checkpoint path via
        ``models/serialization.py``) as the next version of ``name`` and
        hot-swap it in: the incoming version is warmed across all bucket
        shapes BEFORE the atomic flip, in-flight batches finish on the
        old version under their leases, then the old version retires.
        No request is dropped at any point.

        With ``retain_old`` the displaced version is NOT retired: it
        stays loaded in state ``retained`` as the ``rollback`` target —
        the promotion watch window's undo button.  Close the window with
        ``commit_swap`` (keep the new version, retire the old) or
        ``rollback`` (flip back, retire the new).  A still-unresolved
        retained version from an earlier retaining swap is committed
        first — at most one rollback target exists per name."""
        with self._swap_lock:   # serialize swaps per engine
            if isinstance(model_or_path, (str, bytes, os.PathLike)):
                mv = load_version_from_checkpoint(
                    self.models, name, model_or_path, example=example)
            else:
                mv = self.models.new_version(
                    name, model_or_path, example=example, version=version)
            if warmup:
                # only the no-known-shape case is tolerable; a model that
                # FAILS its warmup forward must never be activated — the
                # raise here aborts the swap with the old version intact
                try:
                    warmup_version(mv, self.policy, metrics=self.metrics)
                except NoWarmupShapeError as e:
                    logger.warning("deploying %s unwarmed: %s", mv.key, e)
            # ANY swap supersedes a still-open rollback window: commit it
            # (drain + release the retained weights) rather than letting
            # activate() park the stale version in the history with its
            # model pinned
            self._commit_locked(name, drain_timeout)
            old = self.models.activate(mv, retain=retain_old)
            get_flight_recorder().record(
                "swap", model=name, version=mv.version,
                replaced=old.version if old else None,
                retained=bool(retain_old and old is not None))
            if old is not None:
                self.metrics.swaps.inc(model=name)
                if not retain_old and not self.models.retire(
                        old, timeout=drain_timeout):
                    logger.warning(
                        "old version %s still has in-flight batches after "
                        "%.1fs; left un-retired", old.key, drain_timeout)
            logger.info("%s now serving (replaced %s%s)", mv.key,
                        old.key if old else "nothing",
                        ", retained for rollback"
                        if retain_old and old else "")
            return mv

    def rollback(self, name: str, *,
                 drain_timeout: float = 30.0) -> ModelVersion:
        """Undo the last retaining swap of ``name``: atomically flip the
        active pointer back to the retained previous version, then retire
        the displaced (regressed) version after its in-flight batches
        drain.  Zero requests are dropped: a request leasing during the
        flip completes on whichever version its batch pinned.  Raises
        ``ModelNotFoundError`` when no rollback window is open."""
        with self._swap_lock:
            restored, displaced = self.models.rollback(name)
            get_flight_recorder().record(
                "rollback", model=name, restored=restored.version,
                displaced=displaced.version if displaced else None)
            self.metrics.swaps.inc(model=name)
            logger.warning(
                "%s ROLLED BACK to %s (displacing %s)", name, restored.key,
                displaced.key if displaced else "nothing")
            if displaced is not None and not self.models.retire(
                    displaced, timeout=drain_timeout):
                logger.warning(
                    "rolled-back version %s still has in-flight batches "
                    "after %.1fs; left un-retired", displaced.key,
                    drain_timeout)
            return restored

    def commit_swap(self, name: str, *,
                    drain_timeout: float = 30.0) -> Optional[ModelVersion]:
        """Close the rollback window after a ``deploy(...,
        retain_old=True)`` that watched clean: retire the retained
        previous version (drain, release weights).  Returns it, or None
        when no window was open — committing twice is harmless."""
        with self._swap_lock:
            return self._commit_locked(name, drain_timeout)

    def _commit_locked(self, name: str,
                       drain_timeout: float) -> Optional[ModelVersion]:
        mv = self.models.release_retained(name)
        if mv is not None and not self.models.retire(
                mv, timeout=drain_timeout):
            logger.warning(
                "retained version %s still has in-flight batches after "
                "%.1fs; left un-retired", mv.key, drain_timeout)
        return mv

    # ---------------------------------------------------------------- canary
    def start_canary(self, name: str, model_or_path, *,
                     fraction: float = 0.1, example=None,
                     seed: int = 0) -> ModelVersion:
        """Serve a candidate next to ``name`` on a traffic fraction: the
        candidate is warmed and registered under ``<name>:canary``, and
        each later ``predict(model=name)`` is rerouted to it with
        probability ``fraction`` (seeded).  Outcomes of rerouted requests
        are tallied (``canary_stats``); ``stop_canary`` tears the split
        down again.  The primary version is untouched throughout — a
        canary that fails its warmup never serves a single request."""
        if not 0.0 < fraction <= 1.0:
            raise ValueError(f"fraction must be in (0, 1], got {fraction}")
        with self._swap_lock:
            if name in self._canary:
                raise ValueError(f"{name!r} already has a live canary")
            self.models.active(name)   # primary must exist (raises if not)
            canary_name = f"{name}:canary"
            if isinstance(model_or_path, (str, bytes, os.PathLike)):
                mv = load_version_from_checkpoint(
                    self.models, canary_name, model_or_path, example=example)
            else:
                mv = self.models.new_version(
                    canary_name, model_or_path, example=example)
            try:
                warmup_version(mv, self.policy, metrics=self.metrics)
            except NoWarmupShapeError as e:
                logger.warning("canary %s unwarmed: %s", mv.key, e)
            self.models.activate(mv)
            with self._canary_lock:
                self._canary[name] = _CanaryRoute(canary_name, fraction,
                                                  seed=seed)
            get_flight_recorder().record(
                "canary_start", model=name, version=mv.version,
                fraction=fraction)
            logger.info("canary %s serving %.0f%% of %r traffic", mv.key,
                        100.0 * fraction, name)
            return mv

    def canary_stats(self, name: str) -> Optional[dict]:
        with self._canary_lock:
            route = self._canary.get(name)
        return route.as_dict() if route is not None else None

    def stop_canary(self, name: str, *,
                    drain_timeout: float = 30.0) -> Optional[dict]:
        """Tear down ``name``'s traffic split: stop routing new requests
        to the canary, wait (bounded) until every request already queued
        for the canary name has dispatched — a queued request must never
        fail its lease against a removed registry entry — then retire the
        canary version.  Returns the final outcome tally, or None when no
        canary was live.  The queue wait happens OUTSIDE the swap lock so
        deploys/rollbacks are never blocked behind a canary backlog."""
        with self._swap_lock:
            with self._canary_lock:
                route = self._canary.pop(name, None)
            if route is None:
                return None
            stats = route.as_dict()
            try:
                mv = self.models.active(route.canary_model)
            except ModelNotFoundError:
                mv = None
        deadline = time.monotonic() + drain_timeout
        while (self.batcher.queued_for(route.canary_model) > 0
               and time.monotonic() < deadline):
            time.sleep(0.005)
        with self._swap_lock:
            if name not in self._canary:
                # a NEW canary for this name may have started while we
                # waited; the registry entry then belongs to it — only
                # tear the name down while OUR version still owns it
                try:
                    if self.models.active(route.canary_model) is mv:
                        self.models.remove(route.canary_model)
                except ModelNotFoundError:
                    pass
            if mv is not None and not self.models.retire(
                    mv, timeout=drain_timeout):
                logger.warning(
                    "canary %s still has in-flight batches after %.1fs; "
                    "left un-retired", mv.key, drain_timeout)
            get_flight_recorder().record(
                "canary_stop", model=name,
                version=mv.version if mv else None, **{
                    k: stats[k] for k in
                    ("requests", "judged", "bad", "error_rate")})
            return stats

    def stats(self) -> dict:
        """Live engine state for the HTTP /models endpoint."""
        with self._canary_lock:   # snapshot: start/stop_canary mutate
            canaries = list(self._canary.items())
        return {
            "models": self.models.as_dict(),
            "queue_depth": self.batcher.queued,
            "max_batch": self.policy.max_batch,
            "batch_buckets": list(self.policy.batch_buckets),
            "seq_buckets": (list(self.policy.seq_buckets)
                            if self.policy.seq_buckets else None),
            "max_queue": self.admission.max_queue,
            "dispatcher_alive": self.batcher.is_alive(),
            "canaries": {n: r.as_dict() for n, r in canaries},
        }

    # ------------------------------------------------------------- execution
    def _execute_batch(self, model_name: str, feats: np.ndarray) -> np.ndarray:
        """Forward one concatenated batch under a version lease: chunk to
        the row budget, pad each chunk UP to its bucket (never to full
        ``max_batch`` unless needed), fingerprint through the version's
        recompile detector, slice the padding back off."""
        with step_guard("serving_dispatch", model=model_name,
                        rows=len(feats)):
            return self._execute_leased(model_name, feats)

    def _execute_leased(self, model_name: str, feats: np.ndarray) -> np.ndarray:
        with self.models.lease(model_name) as mv:
            n = len(feats)
            outs = []
            i = 0
            while i < n:
                take = min(self.policy.max_batch, n - i)
                chunk = feats[i:i + take]
                bucket = self.policy.bucket_rows(take)
                if bucket > take:
                    pad = np.zeros((bucket - take,) + chunk.shape[1:],
                                   chunk.dtype)
                    chunk = np.concatenate([chunk, pad])
                self.metrics.bucket_util.observe(take / bucket)
                mv.detector.check((chunk,), {})
                outs.append(np.asarray(mv.model.output(chunk))[:take])
                i += take
            return outs[0] if len(outs) == 1 else np.concatenate(outs)
