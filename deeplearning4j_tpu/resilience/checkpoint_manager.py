"""Async checkpointing with atomic commit, retention, and discovery.

The low-level ``parallel/checkpoint.py`` writes shard files straight into
the live directory — fine for an explicit, supervised save, fatal for a
production run where the writer can die mid-file.  ``CheckpointManager``
is the production path:

- **snapshot on the step boundary** — ``save()`` copies the device arrays
  to host on the calling (training) thread via ``snapshot_trees`` (the
  only part that must see a consistent step), then hands the plain host
  data to a background writer thread: training resumes while serialization
  and fsync happen off-thread;
- **atomic commit** — the writer stages all files in ``step-N.tmp/``,
  fsyncs them, writes a ``COMMIT`` manifest (per-file sizes + CRC32s)
  last, then renames ``step-N.tmp/`` -> ``step-N/`` and fsyncs the parent
  directory.  A crash at ANY point leaves either a previous committed
  checkpoint or an ignorable ``.tmp`` — never a torn ``step-N/``;
- **discovery** — ``latest()`` walks committed directories newest-first
  and returns the first that VERIFIES (every file listed in COMMIT
  present, sizes and CRCs matching), so truncated or bit-flipped
  snapshots are skipped, not served;
- **retention** — keep the newest ``keep`` checkpoints, plus (optionally)
  every ``archive_every_steps``-th step forever (the keep-every-H
  archival tier for post-hoc analysis);
- **triggers** — step interval, wall-clock interval, explicit call, or a
  priority request (what ``PreemptionHandler`` files from its signal
  handler).

Metric families (docs/observability.md): ``dl4j_checkpoint_saves_total``,
``dl4j_checkpoint_save_seconds``, ``dl4j_checkpoint_last_bytes``,
``dl4j_checkpoint_bytes_total``, ``dl4j_checkpoint_failures_total``,
``dl4j_checkpoint_restores_total`` and the
``dl4j_checkpoint_staleness_seconds`` gauge that the
``max_checkpoint_staleness`` HealthRule reads — a run that silently
stopped checkpointing fails ``/health`` before the loss of progress is
discovered the hard way.

Multi-host note: this manager is the single-controller path (every
process's arrays visible to one process, as in this repo's virtual-device
meshes).  A true multi-host pod writes per-process shard files through the
low-level API plus an external commit barrier; the COMMIT protocol here is
deliberately file-based so such a coordinator can adopt it.
"""

from __future__ import annotations

import json
import os
import queue
import shutil
import threading
import time
import weakref
import zlib
from typing import Any, Dict, List, Optional, Tuple

from deeplearning4j_tpu.parallel.checkpoint import (
    restore_checkpoint, snapshot_trees, write_snapshot,
)

_SAVES = "dl4j_checkpoint_saves_total"
_SAVE_SECONDS = "dl4j_checkpoint_save_seconds"
_LAST_BYTES = "dl4j_checkpoint_last_bytes"
_BYTES_TOTAL = "dl4j_checkpoint_bytes_total"
_STALENESS = "dl4j_checkpoint_staleness_seconds"
_RESTORES = "dl4j_checkpoint_restores_total"
_FAILURES = "dl4j_checkpoint_failures_total"

COMMIT_FILE = "COMMIT"
_STEP_PREFIX = "step-"
_STEP_DIGITS = 8


def _crc32(path: str, chunk: int = 1 << 20) -> int:
    crc = 0
    with open(path, "rb") as f:
        while True:
            buf = f.read(chunk)
            if not buf:
                return crc
            crc = zlib.crc32(buf, crc)


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


class CheckpointError(RuntimeError):
    pass


class _Job:
    __slots__ = ("step", "snapshot", "trigger", "done", "error", "bytes")

    def __init__(self, step: int, snapshot: Dict[str, Any], trigger: str):
        self.step = step
        self.snapshot = snapshot
        self.trigger = trigger
        self.done = threading.Event()
        self.error: Optional[BaseException] = None
        self.bytes = 0

    def wait(self, timeout: Optional[float] = None) -> None:
        if not self.done.wait(timeout):
            raise CheckpointError(
                f"checkpoint step-{self.step} not committed within "
                f"{timeout}s")
        if self.error is not None:
            raise self.error


class CheckpointManager:
    """Async atomic checkpointing for one run directory (module docstring).

    Parameters: ``keep`` — committed checkpoints retained (newest-first);
    ``archive_every_steps`` — additionally keep every multiple of this
    step count forever; ``save_every_steps`` / ``save_every_seconds`` —
    ``maybe_save`` triggers; ``async_save=False`` makes every save commit
    on the calling thread (early stopping, tests); ``verify_crc`` — check
    CRC32s during ``latest()`` discovery (sizes are always checked);
    ``fault_injector`` — explicit injector for the writer hooks (defaults
    to the process-global one, see ``resilience.faults``).
    """

    def __init__(self, directory: str, *, keep: int = 3,
                 archive_every_steps: Optional[int] = None,
                 save_every_steps: Optional[int] = None,
                 save_every_seconds: Optional[float] = None,
                 async_save: bool = True, auto_resume: bool = True,
                 verify_crc: bool = True, fault_injector=None,
                 registry=None):
        if keep < 1:
            raise ValueError(f"keep must be >= 1, got {keep}")
        self.directory = str(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.keep = int(keep)
        self.archive_every_steps = archive_every_steps
        self.save_every_steps = save_every_steps
        self.save_every_seconds = save_every_seconds
        self.async_save = bool(async_save)
        self.auto_resume = bool(auto_resume)
        self.verify_crc = bool(verify_crc)
        self._injector = fault_injector
        self._registry = registry
        self._lock = threading.Lock()
        self._priority = False
        self._start_mono = time.monotonic()
        self._last_commit_mono: Optional[float] = None
        self._last_mark_step = 0           # last step a save was TRIGGERED at
        self._last_mark_time = time.monotonic()
        self._last_queued_step: Optional[int] = None
        self.last_committed_step: Optional[int] = None
        self.last_error: Optional[BaseException] = None
        self._queue: "queue.Queue[Optional[_Job]]" = queue.Queue(maxsize=1)
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        self._register_staleness_gauge()

    # ------------------------------------------------------------- metrics
    def _reg(self):
        if self._registry is not None:
            return self._registry
        from deeplearning4j_tpu.observability import get_registry

        return get_registry()

    def _register_staleness_gauge(self) -> None:
        ref = weakref.ref(self)

        def staleness() -> float:
            m = ref()
            if m is None:
                return float("nan")
            with m._lock:
                last = (m._last_commit_mono if m._last_commit_mono is not None
                        else m._start_mono)
            return time.monotonic() - last

        # the ABSOLUTE path: basenames collide (every CheckpointModelSaver
        # has a "best/" and a "latest/"), and a collision replaces the
        # other manager's gauge callback — blinding the staleness rule
        self.label = os.path.abspath(self.directory)
        self._reg().gauge(
            _STALENESS, "Seconds since this manager's last committed (or "
            "restored) checkpoint — counted from manager creation before "
            "the first commit, so a run that never checkpoints also trips "
            "the max_checkpoint_staleness health rule",
            labels=("directory",)
        ).set_function(staleness, directory=self.label)

    # ----------------------------------------------------------- discovery
    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory,
                            f"{_STEP_PREFIX}{step:0{_STEP_DIGITS}d}")

    def _committed(self) -> List[Tuple[int, str]]:
        """(step, path) of committed (renamed) checkpoint dirs, ascending.
        Validity is NOT checked here — ``latest()`` does that on demand."""
        out = []
        try:
            names = os.listdir(self.directory)
        except FileNotFoundError:
            return []
        for name in names:
            if not name.startswith(_STEP_PREFIX) or name.endswith(".tmp"):
                continue
            try:
                step = int(name[len(_STEP_PREFIX):])
            except ValueError:
                continue
            out.append((step, os.path.join(self.directory, name)))
        return sorted(out)

    def read_commit(self, path: str) -> Optional[Dict[str, Any]]:
        try:
            with open(os.path.join(path, COMMIT_FILE)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def _valid(self, path: str) -> bool:
        """A committed checkpoint verifies iff every file its COMMIT
        manifest lists exists with the recorded size (and CRC32, when
        ``verify_crc``)."""
        commit = self.read_commit(path)
        if not commit or not commit.get("files"):
            return False
        for name, info in commit["files"].items():
            p = os.path.join(path, name)
            try:
                if os.path.getsize(p) != info["size"]:
                    return False
                if self.verify_crc and _crc32(p) != info["crc32"]:
                    return False
            except OSError:
                return False
        return True

    def latest(self) -> Optional[str]:
        """Path of the newest VALID committed checkpoint (torn, truncated,
        or corrupted snapshots are skipped), or None."""
        for step, path in reversed(self._committed()):
            if self._valid(path):
                return path
        return None

    def latest_step(self) -> Optional[int]:
        path = self.latest()
        if path is None:
            return None
        commit = self.read_commit(path)
        return int(commit["step"]) if commit else None

    def all_steps(self) -> List[int]:
        return [s for s, _ in self._committed()]

    # ------------------------------------------------------------- triggers
    def request_priority_save(self) -> None:
        """Flag a priority save (async-signal-safe: plain attribute set).
        The next ``maybe_save``/``due`` honors it regardless of
        intervals.  Deliberately lock-free: this runs inside the SIGTERM
        handler, which executes on the main thread — if that thread
        already holds ``_lock`` (mid-``save``), acquiring it here would
        self-deadlock.  A one-way bool flip is atomic under the GIL and
        ``save`` clears it under the lock afterwards."""
        # dl4jlint: disable-next-line=lock-discipline -- signal-handler path: taking _lock here can self-deadlock; atomic bool publish
        self._priority = True

    def due(self, step: Optional[int] = None) -> Optional[str]:
        """The trigger that makes a save due now, or None."""
        # dl4jlint: disable-next-line=lock-discipline -- atomic bool read of the signal-published flag; save() clears it under _lock
        if self._priority:
            return "priority"
        with self._lock:
            mark_step, mark_time = self._last_mark_step, self._last_mark_time
        if (self.save_every_steps is not None and step is not None
                and step - mark_step >= self.save_every_steps):
            return "step_interval"
        if (self.save_every_seconds is not None
                and time.monotonic() - mark_time >= self.save_every_seconds):
            return "time_interval"
        return None

    def maybe_save(self, net, block: bool = False) -> Optional[str]:
        """Save if a trigger is due; returns the trigger used or None.
        The fit loops call this once per step/window boundary."""
        trigger = self.due(int(getattr(net, "iteration", 0)))
        if trigger is None:
            return None
        self.save(net, trigger=trigger, block=block)
        return trigger

    def save_if_stale(self, net, trigger: str = "preempt",
                      block: bool = True) -> bool:
        """Commit the current state unless a save at this step was already
        queued/committed; used on the preemption path so the stop never
        double-writes.  Always drains the writer when ``block``."""
        step = int(getattr(net, "iteration", 0))
        with self._lock:
            covered = (self._last_queued_step is not None
                       and self._last_queued_step >= step)
        if covered and block:
            # a QUEUED save only covers the step if it actually COMMITS —
            # an async write failure (ENOSPC, IO error) must not skip the
            # last-chance preemption save
            self.wait_idle()
            with self._lock:
                covered = (self.last_committed_step is not None
                           and self.last_committed_step >= step)
        if not covered:
            self.save(net, trigger=trigger, block=block)
            return True
        return False

    # ----------------------------------------------------------------- save
    def save(self, net, *, trigger: str = "explicit",
             block: Optional[bool] = None, trees=None) -> _Job:
        """Snapshot now (device->host on this thread), commit async (or
        inline when ``async_save=False`` / ``block=True`` waits)."""
        if self._closed:
            raise CheckpointError("CheckpointManager is closed")
        try:
            snapshot = snapshot_trees(net, trees=trees)
        except BaseException:
            self._reg().counter(
                _FAILURES, "Checkpoint attempts that failed, by stage "
                "(snapshot = device->host copy on the training thread, "
                "write = staging/commit on the writer)",
                labels=("stage",)).inc(stage="snapshot")
            raise
        job = _Job(snapshot["iteration"], snapshot, trigger)
        with self._lock:
            self._last_mark_step = job.step
            self._last_mark_time = time.monotonic()
            self._last_queued_step = max(self._last_queued_step or 0,
                                         job.step)
            if trigger in ("priority", "preempt"):
                self._priority = False
        if not self.async_save:
            self._run_job(job)
            job.wait(0)
            return job
        self._ensure_thread()
        # maxsize-1 queue: if a previous save is still staging, this put
        # blocks — backpressure instead of a growing host-memory backlog
        self._queue.put(job)
        if block:
            job.wait()
        return job

    def wait_idle(self, timeout: Optional[float] = None) -> None:
        """Block until every queued save has committed (or failed)."""
        if self._thread is None:
            return
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            with self._queue.mutex:
                idle = self._queue.unfinished_tasks == 0
            if idle:
                return
            if deadline is not None and time.monotonic() > deadline:
                raise CheckpointError(f"writer still busy after {timeout}s")
            time.sleep(0.005)

    def close(self) -> None:
        """Drain and stop the writer thread."""
        if self._closed:
            return
        self._closed = True
        if self._thread is not None:
            self._queue.put(None)
            self._thread.join(timeout=30)
            self._thread = None

    def __enter__(self) -> "CheckpointManager":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False

    # ------------------------------------------------------------ writer
    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._writer_loop, name="dl4j-checkpoint-writer",
                daemon=True)
            self._thread.start()

    def _writer_loop(self) -> None:
        while True:
            job = self._queue.get()
            try:
                if job is None:
                    return
                self._run_job(job)
            finally:
                self._queue.task_done()

    def _on_file(self, path: str) -> None:
        inj = self._injector
        if inj is None:
            from deeplearning4j_tpu.resilience.faults import get_fault_injector

            inj = get_fault_injector()
        if inj is not None:
            inj.on_checkpoint_file(path)

    def _run_job(self, job: _Job) -> None:
        from deeplearning4j_tpu.observability import get_flight_recorder

        reg = self._reg()
        t0 = time.perf_counter()
        try:
            job.bytes = self._commit(job)
        except BaseException as e:
            job.error = e
            self.last_error = e
            reg.counter(
                _FAILURES, "Checkpoint attempts that failed, by stage "
                "(snapshot = device->host copy on the training thread, "
                "write = staging/commit on the writer)",
                labels=("stage",)).inc(stage="write")
            get_flight_recorder().record(
                "checkpoint_error", step=job.step, trigger=job.trigger,
                error=repr(e))
            return
        finally:
            job.done.set()
        dt = time.perf_counter() - t0
        with self._lock:
            self._last_commit_mono = time.monotonic()
            self.last_committed_step = job.step
        reg.counter(
            _SAVES, "Committed checkpoint saves by trigger "
            "(step_interval / time_interval / priority / preempt / "
            "explicit / best / latest / final)",
            labels=("trigger",)).inc(trigger=job.trigger)
        reg.histogram(
            _SAVE_SECONDS, "Serialize + fsync + atomic-commit wall time "
            "per checkpoint (writer thread; excludes the on-thread "
            "device->host snapshot)").observe(dt)
        reg.gauge(_LAST_BYTES,
                  "Bytes of the most recently committed checkpoint"
                  ).set(float(job.bytes))
        reg.counter(_BYTES_TOTAL,
                    "Total checkpoint bytes committed by this process"
                    ).inc(float(job.bytes))
        get_flight_recorder().record(
            "checkpoint", directory=self.directory, step=job.step,
            trigger=job.trigger, bytes=job.bytes,
            seconds=round(dt, 4), committed=True)

    def _commit(self, job: _Job) -> int:
        final = self._step_dir(job.step)
        if os.path.isdir(final):
            if self._valid(final):
                return 0    # an identical step is already committed
            shutil.rmtree(final)
        tmp = final + ".tmp"
        if os.path.isdir(tmp):
            shutil.rmtree(tmp)
        nbytes = write_snapshot(tmp, job.snapshot, fsync=True,
                                on_file=self._on_file)
        files = {}
        for name in sorted(os.listdir(tmp)):
            p = os.path.join(tmp, name)
            files[name] = {"size": os.path.getsize(p), "crc32": _crc32(p)}
        commit = {
            "format_version": 1,
            "step": job.step,
            "iteration": job.snapshot["iteration"],
            "trigger": job.trigger,
            "wall_time": time.time(),
            "files": files,
        }
        commit_path = os.path.join(tmp, COMMIT_FILE)
        with open(commit_path, "w") as f:
            json.dump(commit, f)
            f.flush()
            os.fsync(f.fileno())
        nbytes += os.path.getsize(commit_path)
        self._on_file(commit_path)
        os.rename(tmp, final)          # the commit point
        _fsync_dir(self.directory)
        self._prune()
        return nbytes

    # ------------------------------------------------------------ retention
    def _prune(self) -> None:
        committed = self._committed()
        steps = [s for s, _ in committed]
        protect = set(steps[-self.keep:])
        if self.archive_every_steps:
            protect |= {s for s in steps
                        if s and s % self.archive_every_steps == 0}
        for step, path in committed:
            if step not in protect:
                shutil.rmtree(path, ignore_errors=True)
        # stray .tmp dirs from crashed writers are dead weight once a
        # newer commit exists (the single writer thread means none can be
        # in flight while we are here)
        for name in os.listdir(self.directory):
            if name.endswith(".tmp"):
                shutil.rmtree(os.path.join(self.directory, name),
                              ignore_errors=True)

    # -------------------------------------------------------------- restore
    def restore(self, net=None, *, mesh=None, step: Optional[int] = None,
                path: Optional[str] = None):
        """Restore the newest valid checkpoint (or ``step=``) into ``net``
        (in place, incl. iteration + RNG stream).  Returns
        ``(params, updater_state, net_state, iteration)`` like the
        low-level API; raises ``FileNotFoundError`` when nothing valid is
        committed.  ``path=`` skips discovery for an ALREADY-VALIDATED
        directory (``resume()`` passes the one its ``latest()`` call just
        CRC-verified, so the full scan is not paid twice)."""
        from deeplearning4j_tpu.observability import get_flight_recorder

        if path is None:
            if step is not None:
                path = self._step_dir(step)
                if not self._valid(path):
                    raise FileNotFoundError(
                        f"no valid committed checkpoint for step {step} in "
                        f"{self.directory}")
            else:
                path = self.latest()
                if path is None:
                    raise FileNotFoundError(
                        f"no valid committed checkpoint in {self.directory}")
        out = restore_checkpoint(path, net, mesh=mesh)
        self._reg().counter(
            _RESTORES, "Checkpoint restores served by a CheckpointManager "
            "(explicit restore() plus fit-loop auto-resume)").inc()
        with self._lock:
            # a fresh restore is as good as a fresh commit for staleness:
            # the recoverable point IS this checkpoint
            self._last_commit_mono = time.monotonic()
            self._last_mark_step = out[3]
            self._last_mark_time = time.monotonic()
        attrs = {}
        if mesh is not None:
            # topology portability (parallel.checkpoint resharded restore):
            # record WHERE the snapshot landed — a resumed-on-a-new-mesh or
            # promoted-into-serving restore is visible in the post-mortem
            attrs["mesh"] = "x".join(
                str(mesh.shape[a]) for a in mesh.axis_names)
        get_flight_recorder().record(
            "checkpoint_restore", directory=self.directory,
            path=path, iteration=out[3], **attrs)
        return out

    def resume(self, net, *, mesh=None) -> Optional[int]:
        """Auto-resume: when a valid committed checkpoint is AHEAD of
        ``net`` (its iteration exceeds ``net.iteration``), restore it in
        place and return the restored iteration; otherwise leave ``net``
        untouched and return None.  The fit loops call this on entry when
        given a manager with ``auto_resume=True``.

        ``mesh`` need NOT match the topology that saved: the resharded
        restore (``parallel.checkpoint``) maps any saved layout onto any
        target mesh — a 2x4 checkpoint resumes on 1x8, a K=4 run resumes
        on K=2, a training snapshot promotes into a differently-sharded
        serving mesh — with no global host gather of a sharded leaf.

        Cost discipline: the cheap COMMIT manifest decides "is it ahead?"
        BEFORE the full size+CRC verification — a fit entry that has
        nothing to resume (the common case) never reads the checkpoint
        bytes.  Torn/invalid snapshots still fall through to older ones."""
        entry = int(getattr(net, "iteration", 0))
        for step, path in reversed(self._committed()):
            commit = self.read_commit(path)
            if commit is None:
                continue               # torn: no COMMIT — try older
            if int(commit["iteration"]) <= entry:
                return None            # newest committed is not ahead
            if self._valid(path):
                self.restore(net, mesh=mesh, path=path)
                return int(net.iteration)
            # corrupt despite COMMIT: an older snapshot may still be ahead
        return None
