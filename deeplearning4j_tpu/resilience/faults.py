"""Deterministic fault injection — the chaos harness the resilience tests
drive the REAL code paths with.

The reference stack's fault tolerance was exercised by Spark killing
executors; here the equivalent is a seeded ``FaultInjector`` that the fit
loops, the checkpoint writer, and the worker-telemetry seams consult at
well-defined points:

- ``fail_at_step(n)`` — the fit loops call ``on_step(component, step)``
  inside their retry scope, so an injected step fault exercises the real
  ``RetryPolicy`` backoff (transient) or the real crash-dump path (fatal);
- ``crash_after_files(n)`` — ``CheckpointManager``'s writer calls
  ``on_checkpoint_file(path)`` after each staged file, so the injector can
  kill the writer BETWEEN shard files, leaving exactly the torn ``.tmp``
  directory a preempted VM would;
- ``delay_worker(k, seconds)`` — the in-process worker-timing seams add the
  delay to worker ``k``'s reported step time, turning the straggler
  detector's input deterministic (and, when an ``ElasticController`` is
  attached, the synchrony-barrier simulation actually stalls the window
  by the slowest ACTIVE worker's delay — the lockstep collapse the
  elasticity layer exists to fix);
- ``hang_worker(k)`` / ``kill_worker(k, at_step)`` — mark a worker hung
  (stops responding) or dead (process gone) from a given step; the
  elastic layer polls ``worker_state(k, step)`` at every window boundary
  and evicts, and ``until_step`` / ``clear_worker`` model the fault
  clearing so re-admission paths are just as deterministic;
- ``poison_gradients(k, at_step, mode=nan|inf|spike, until_step=)`` —
  worker ``k``'s minibatch features are poisoned before dispatch (the
  fit loops and both data-parallel masters consult ``poison_batch`` /
  ``poison_replica_slots`` / ``poison_rows``), so the injected NaN/Inf/
  spike flows through the REAL forward/backward into the loss and
  gradients — the deterministic harness for the stability engine's
  device-side guard, per-replica poison masking, and divergence
  sentinel (``resilience/stability.py``);
- ``corrupt_checkpoint(dir)`` — post-hoc bit-flip / truncation / marker
  deletion of a COMMITTED checkpoint, for proving ``latest()`` skips torn
  snapshots.

Everything is seeded (``random.Random(seed)``) and counts deterministically
— the same test run injects the same faults in the same order.
"""

from __future__ import annotations

import os
import random
import threading
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

from deeplearning4j_tpu.resilience.retry import TransientError


class InjectedFault(RuntimeError):
    """A fault raised by the FaultInjector (fatal flavor)."""


class TransientInjectedFault(TransientError, InjectedFault):
    """A fault the RetryPolicy classifies as transient (retryable)."""


class FaultInjector:
    """Seeded, deterministic fault harness (see module docstring).

    All arming calls return ``self`` so rules chain::

        inj = (FaultInjector(seed=7)
               .fail_at_step(3, transient=True)
               .crash_after_files(1))
        with inject_faults(inj):
            net.fit(iterator, checkpoint_manager=cm, retry_policy=rp)
    """

    def __init__(self, seed: int = 0):
        self.seed = int(seed)
        self.rng = random.Random(seed)
        self._lock = threading.Lock()
        self._step_rules: List[Dict[str, Any]] = []
        self._file_crash_after: Optional[int] = None
        self._file_crash_exc: Optional[BaseException] = None
        self._files_seen = 0
        self._worker_delays: Dict[str, float] = {}
        self._worker_states: List[Dict[str, Any]] = []
        self._poison_rules: List[Dict[str, Any]] = []
        self.injected: List[Dict[str, Any]] = []   # what fired, in order

    # ------------------------------------------------------------ step faults
    def fail_at_step(self, step: int, exc: Optional[BaseException] = None, *,
                     component: Optional[str] = None, times: int = 1,
                     transient: bool = True) -> "FaultInjector":
        """Raise when a fit loop reaches global iteration ``step`` (fires
        ``times`` times, then disarms; ``component`` narrows to one loop)."""
        with self._lock:   # arming can race a live run's on_step scan
            self._step_rules.append({
                "step": int(step), "component": component,
                "times": int(times), "exc": exc, "transient": transient,
            })
        return self

    def on_step(self, component: str, step: int) -> None:
        """Called by the fit loops at each step boundary (inside the retry
        scope).  Raises if an armed rule matches."""
        fire = None
        with self._lock:
            for rule in self._step_rules:
                if rule["times"] <= 0:
                    continue
                if rule["step"] != int(step):
                    continue
                if rule["component"] and rule["component"] != component:
                    continue
                rule["times"] -= 1
                fire = rule
                break
            if fire is not None:
                self.injected.append({"kind": "step_fault",
                                      "component": component, "step": step})
        if fire is None:
            return
        if fire["exc"] is not None:
            raise fire["exc"]
        if fire["transient"]:
            raise TransientInjectedFault(
                f"injected transient fault at {component} step {step}")
        raise InjectedFault(
            f"injected fatal fault at {component} step {step}")

    # ------------------------------------------------------- writer crashes
    def crash_after_files(self, n: int,
                          exc: Optional[BaseException] = None
                          ) -> "FaultInjector":
        """Kill the checkpoint writer after the ``n``-th staged file lands
        (n=1 → crash between the shard file and the manifest)."""
        with self._lock:   # the async writer thread reads these in
            self._file_crash_after = int(n)   # on_checkpoint_file
            self._file_crash_exc = exc
            self._files_seen = 0
        return self

    def on_checkpoint_file(self, path: str) -> None:
        """Called by ``write_snapshot`` after each staged checkpoint file."""
        with self._lock:
            if self._file_crash_after is None:
                return
            self._files_seen += 1
            if self._files_seen != self._file_crash_after:
                return
            self._file_crash_after = None   # one-shot
            self.injected.append({"kind": "writer_crash", "path": path})
            exc = self._file_crash_exc
        raise exc if exc is not None else InjectedFault(
            f"injected writer crash after {path}")

    # --------------------------------------------------------- slow workers
    def delay_worker(self, worker, seconds: float) -> "FaultInjector":
        """Make worker ``k`` look ``seconds`` slower to the telemetry seams
        (deterministic straggler)."""
        with self._lock:   # elastic tests (re)arm this mid-run
            self._worker_delays[str(worker)] = float(seconds)
        return self

    def worker_delay(self, worker) -> float:
        with self._lock:
            return self._worker_delays.get(str(worker), 0.0)

    def clear_worker_delay(self, worker) -> "FaultInjector":
        """Remove an armed ``delay_worker`` (the straggler recovered)."""
        with self._lock:
            self._worker_delays.pop(str(worker), None)
        return self

    # ------------------------------------------------------ hung/dead workers
    def hang_worker(self, worker, at_step: int = 0, *,
                    until_step: Optional[int] = None) -> "FaultInjector":
        """Worker ``k`` stops responding from global step ``at_step``
        (state ``"hung"``): it never reports a step result, so a lockstep
        run stalls on it forever while an elastic run evicts it at the
        next window boundary.  ``until_step`` models the hang clearing on
        its own (deterministic re-admission tests); ``clear_worker`` does
        it explicitly."""
        with self._lock:   # arming can race worker_state polls
            self._worker_states.append({
                "worker": str(worker), "kind": "hung",
                "at_step": int(at_step),
                "until_step": None if until_step is None
                else int(until_step),
                "fired": False,
            })
        return self

    def kill_worker(self, worker, at_step: int, *,
                    until_step: Optional[int] = None) -> "FaultInjector":
        """Worker ``k`` dies at global step ``at_step`` (state ``"dead"``
        — the per-worker SIGTERM / preempted-VM case).  ``until_step``
        models a replacement worker coming back for re-admission."""
        with self._lock:
            self._worker_states.append({
                "worker": str(worker), "kind": "dead",
                "at_step": int(at_step),
                "until_step": None if until_step is None
                else int(until_step),
                "fired": False,
            })
        return self

    def clear_worker(self, worker) -> "FaultInjector":
        """Clear every armed hang/kill/poison for ``worker`` (the fault
        is over; an elastic run re-admits at the next window boundary)."""
        worker = str(worker)
        with self._lock:
            self._worker_states = [r for r in self._worker_states
                                   if r["worker"] != worker]
            self._poison_rules = [r for r in self._poison_rules
                                  if r["worker"] != worker]
        return self

    def worker_state(self, worker, step: int) -> str:
        """``"ok"`` | ``"hung"`` | ``"dead"`` | ``"poisoned"`` for
        ``worker`` at global ``step`` — the elastic layer polls this at
        window boundaries.  ``dead`` > ``hung`` > ``poisoned`` when
        several are armed.  A poisoned worker is NOT evicted on sight:
        the device-side guard weights it out per window, and eviction
        comes from the repeat-offender count (``TrainingStability.
        poison_evict_after``) — but the state keeps an evicted
        ``"poisoned"`` replica out until the rule clears."""
        worker = str(worker)
        state = "ok"
        with self._lock:
            for rule in self._worker_states:
                if rule["worker"] != worker:
                    continue
                if int(step) < rule["at_step"]:
                    continue
                if (rule["until_step"] is not None
                        and int(step) >= rule["until_step"]):
                    continue
                if not rule["fired"]:
                    rule["fired"] = True
                    self.injected.append({
                        "kind": f"worker_{rule['kind']}", "worker": worker,
                        "step": int(step)})
                if rule["kind"] == "dead":
                    return "dead"
                state = "hung"
        if state == "ok" and self.poison_mode(worker, step) is not None:
            state = "poisoned"
        return state

    # ----------------------------------------------------- gradient poison
    def poison_gradients(self, worker, at_step: int = 0,
                         mode: str = "nan", *,
                         until_step: Optional[int] = None
                         ) -> "FaultInjector":
        """Worker ``k`` produces poisoned gradients from global step
        ``at_step`` (same arming shape as ``hang_worker``/``kill_worker``).
        Deterministically applied by the fit loops / parallel masters to
        the worker's minibatch features BEFORE dispatch — poisoned data
        is exactly the motivating failure (one bad batch/replica writes
        NaN into params and the all-reduce broadcasts it), and it drives
        the REAL device-side guard rather than a mock.  Modes: ``nan``
        (features become NaN), ``inf`` (become +Inf), ``spike``
        (scaled by 1e4 — finite but divergent, for sentinel tests).
        ``until_step`` models the poison clearing (re-admission tests);
        ``clear_worker`` clears it explicitly.  Single-device fit loops
        poison under worker id ``"0"``."""
        if mode not in ("nan", "inf", "spike"):
            raise ValueError(f"unknown poison mode {mode!r}")
        with self._lock:   # arming can race a live run's poison polls
            self._poison_rules.append({
                "worker": str(worker), "mode": mode,
                "at_step": int(at_step),
                "until_step": None if until_step is None
                else int(until_step),
                "fired": False,
            })
        return self

    def has_poison(self) -> bool:
        """Cheap hot-loop gate: any poison rule armed at all."""
        with self._lock:
            return bool(self._poison_rules)

    def poison_mode(self, worker, step: int) -> Optional[str]:
        """The poison mode active for ``worker`` at ``step``, or None."""
        worker = str(worker)
        with self._lock:
            for rule in self._poison_rules:
                if rule["worker"] != worker:
                    continue
                if int(step) < rule["at_step"]:
                    continue
                if (rule["until_step"] is not None
                        and int(step) >= rule["until_step"]):
                    continue
                if not rule["fired"]:
                    rule["fired"] = True
                    self.injected.append({
                        "kind": "worker_poisoned", "worker": worker,
                        "mode": rule["mode"], "step": int(step)})
                return rule["mode"]
        return None

    @staticmethod
    def _apply_poison(mode: str, arr):
        import numpy as np

        arr = np.array(arr, copy=True)
        if not np.issubdtype(arr.dtype, np.floating):
            return arr                     # integer ids cannot be non-finite
        if mode == "nan":
            arr[...] = np.nan
        elif mode == "inf":
            arr[...] = np.inf
        else:                              # spike: finite but divergent
            arr *= 1e4
        return arr

    def _poison_tree(self, mode: str, tree):
        """Apply poison to every floating array of a (possibly nested)
        features structure; returns a poisoned copy."""
        import numpy as np

        if isinstance(tree, dict):
            return {k: self._poison_tree(mode, v) for k, v in tree.items()}
        if isinstance(tree, (list, tuple)):
            return type(tree)(self._poison_tree(mode, v) for v in tree)
        arr = np.asarray(tree)
        return self._apply_poison(mode, arr)

    def poison_batch(self, worker, step: int, x, y):
        """Single-device hook (both facades): poison this step's features
        when a rule for ``worker`` is live.  Labels are left alone — the
        forward pass propagates the poison into loss AND gradients."""
        mode = self.poison_mode(worker, step)
        if mode is None:
            return x, y
        return self._poison_tree(mode, x), y

    def poison_replica_slots(self, worker_ids, step: int, xs):
        """ParallelWrapper hook: ``xs`` is the stacked ``[F, K, B, ...]``
        window; replica ``k``'s slot is ``xs[:, k]``."""
        import numpy as np

        out = None
        for k, worker in enumerate(worker_ids):
            mode = self.poison_mode(worker, step)
            if mode is None:
                continue
            if out is None:
                out = np.array(xs, copy=True)
            out[:, k] = self._apply_poison(mode, out[:, k])
        return xs if out is None else out

    def poison_rows(self, worker_ids, step: int, features, n_slots: int):
        """SyncTrainingMaster hook: data slot ``k`` owns the contiguous
        row block ``[k*B/K, (k+1)*B/K)`` of the global batch."""
        import numpy as np

        out = None
        per = len(features) // n_slots
        for k, worker in enumerate(worker_ids):
            mode = self.poison_mode(worker, step)
            if mode is None:
                continue
            if out is None:
                out = np.array(features, copy=True)
            rows = slice(k * per, (k + 1) * per)
            out[rows] = self._apply_poison(mode, out[rows])
        return features if out is None else out

    # --------------------------------------------------- on-disk corruption
    def corrupt_checkpoint(self, directory: str, mode: str = "truncate"
                           ) -> str:
        """Damage a COMMITTED checkpoint directory in place; returns the
        path touched.  Modes: ``truncate`` (cut a shard file in half),
        ``corrupt`` (flip bytes at a seeded offset, size unchanged),
        ``drop_commit`` (delete the COMMIT marker).  ``latest()`` must
        refuse the result in every mode."""
        if mode == "drop_commit":
            path = os.path.join(directory, "COMMIT")
            os.remove(path)
            with self._lock:
                self.injected.append({"kind": "corrupt", "mode": mode,
                                      "path": path})
            return path
        shards = sorted(f for f in os.listdir(directory)
                        if f.startswith("shards-"))
        if not shards:
            raise FileNotFoundError(f"no shard files in {directory}")
        path = os.path.join(directory, shards[0])
        size = os.path.getsize(path)
        if mode == "truncate":
            with open(path, "r+b") as f:
                f.truncate(max(1, size // 2))
        elif mode == "corrupt":
            with self._lock:   # reset() swaps self.rng concurrently
                off = self.rng.randrange(max(1, size - 8))
            with open(path, "r+b") as f:
                f.seek(off)
                chunk = f.read(8)
                f.seek(off)
                f.write(bytes(b ^ 0xFF for b in chunk))
        else:
            raise ValueError(f"unknown corruption mode {mode!r}")
        with self._lock:
            self.injected.append({"kind": "corrupt", "mode": mode,
                                  "path": path})
        return path

    def reset(self) -> None:
        with self._lock:
            self._step_rules.clear()
            self._file_crash_after = None
            self._files_seen = 0
            self._worker_delays.clear()
            self._worker_states.clear()
            self._poison_rules.clear()
            self.injected.clear()
            self.rng = random.Random(self.seed)


_active: Optional[FaultInjector] = None


def get_fault_injector() -> Optional[FaultInjector]:
    """The active injector, or None (the production value — every hook
    site is a single global read + None check)."""
    return _active


def set_fault_injector(inj: Optional[FaultInjector]) -> Optional[FaultInjector]:
    global _active
    _active = inj
    return inj


@contextmanager
def inject_faults(inj: FaultInjector):
    """Scope an injector over a block (tests)."""
    global _active
    prev = _active
    _active = inj
    try:
        yield inj
    finally:
        _active = prev
