"""Training stability engine: device-side non-finite step guard, dynamic
loss scaling, and a host-side divergence sentinel with auto-rewind.

The production spine can see, serve, diagnose, and survive crashes — but
nothing protected a *live, healthy* run from numerical failure: one NaN
gradient (bad batch, fp16 overflow, a poisoned replica) silently writes
NaN into the params and the Adam moments, and in the data-parallel
masters the all-reduce broadcasts the poison to every healthy replica.
The reference shipped gradient-level guards as first-class capability
(``GradientNormalization``, ``InvalidScoreIterationTerminationCondition``);
this module is that idea rebuilt for the one-XLA-program world:

- **non-finite step guard** (jit-safe half, used INSIDE every train
  step): an all-finite reduction over loss + gradients, with the skip
  folded into the update as a device-side mask
  (``params = where(finite, new, old)``, updater state and net state
  likewise) — a poisoned step is a no-op with zero host syncs and zero
  recompiles, and a device counter in the stability state records it;
- **loss scaling** (``TrainingStability.loss_scaling``): bf16/fp16
  compute under fp32 master params is only safe when small gradients
  don't flush to zero — the loss is multiplied by a scale before
  ``grad``, gradients are unscaled before the updater, and in
  ``dynamic`` mode the scale halves on overflow (a non-finite step) and
  grows after ``loss_scale_growth_interval`` consecutive finite steps.
  The scale state rides in the jitted step as part of the updater-state
  pytree (``STATE_KEY`` subtree), so it shards, donates, and
  checkpoints exactly like the Adam moments;
- **divergence sentinel** (``StabilityRuntime``, host half): polled at
  fit-loop boundaries every ``check_every`` steps (the ONLY points the
  engine syncs device values), it watches the non-finite counter and a
  rolling finite-loss baseline, and escalates: skip (free, device-side)
  -> LR backoff (a device-carried multiplier on the update, exact for
  every updater, zero recompiles) -> auto-rewind to the newest
  ``CheckpointManager`` snapshot taken while the run was still healthy
  (params/updater/RNG/iteration restored — PR-5 ``FitResilience``
  replay semantics).  Every escalation is a flight event + metric;
- **per-replica poison masking** (used by ``ParallelWrapper`` /
  ``SyncTrainingMaster``): a replica whose window produced non-finite
  gradients is weighted out of that window's average with the same
  runtime ``[K]`` weight mask the elastic layer uses (zero recompiles);
  a repeat offender is handed to the ``ElasticController`` as eviction
  reason ``"poisoned"``.

Metric families (docs/observability.md): ``dl4j_nonfinite_steps_total``,
``dl4j_loss_scale``, ``dl4j_stability_lr_scale``,
``dl4j_divergence_backoffs_total``, ``dl4j_divergence_rewinds_total``,
``dl4j_poisoned_replica_windows_total``; the ``max_nonfinite_steps`` and
``max_divergence_rewinds`` health rules read the counters.
"""

from __future__ import annotations

import collections
import math
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

# Reserved subtree of the updater-state pytree.  Living inside updater
# state means the scale/guard state is stacked per replica by
# ParallelWrapper, sharded by the masters, donated with the step, and
# checkpointed/restored by CheckpointManager without any extra plumbing.
STATE_KEY = "__stability__"

_NONFINITE = "dl4j_nonfinite_steps_total"
_LOSS_SCALE = "dl4j_loss_scale"
_LR_SCALE = "dl4j_stability_lr_scale"
_BACKOFFS = "dl4j_divergence_backoffs_total"
_REWINDS = "dl4j_divergence_rewinds_total"
_POISONED = "dl4j_poisoned_replica_windows_total"


# ---------------------------------------------------------------------------
# jit-safe half: called INSIDE the train steps (no host syncs anywhere here)
# ---------------------------------------------------------------------------

def initial_state(policy) -> Dict[str, jax.Array]:
    """Fresh device-side stability state (one scalar each; the facades
    add it to ``updater_state`` at ``init()``)."""
    scale = policy.loss_scale if policy.loss_scaling != "none" else 1.0
    return {
        "loss_scale": jnp.asarray(scale, jnp.float32),
        "growth_streak": jnp.zeros((), jnp.float32),
        "lr_scale": jnp.ones((), jnp.float32),
        "nonfinite_total": jnp.zeros((), jnp.float32),
    }


def ensure_state(net) -> None:
    """Make sure a stability-enabled net carries the state subtree (nets
    initialized before the policy was set, deserialized nets)."""
    policy = getattr(net.conf, "stability", None)
    if policy is not None and STATE_KEY not in net.updater_state:
        net.updater_state[STATE_KEY] = initial_state(policy)


def split_state(upd_state):
    """(stability subtree, remaining updater state) — trace-time split;
    the remaining dict is what ``updaters.update`` understands."""
    stab = upd_state[STATE_KEY]
    inner = {k: v for k, v in upd_state.items() if k != STATE_KEY}
    return stab, inner


def scaled_loss(loss_fn, stab):
    """Wrap a ``(loss, aux)`` loss function so ``grad`` differentiates
    ``loss * loss_scale`` while the RAW loss stays observable in aux."""

    def f(params, net_state, *args, **kwargs):
        loss, aux = loss_fn(params, net_state, *args, **kwargs)
        return loss * stab["loss_scale"], (loss, aux)

    return f


def all_finite(loss, grads) -> jax.Array:
    """Scalar bool: the loss and every gradient leaf are finite.

    One reduction per leaf: a leaf containing NaN or ±Inf makes its sum
    non-finite (Inf terms of opposite sign collapse to NaN), so
    ``isfinite(Σ leaf-sums + loss)`` is the whole verdict — the classic
    mixed-precision overflow check, half the passes of a per-element
    ``isfinite``-then-``all``."""
    total = jnp.asarray(loss, jnp.float32)
    for leaf in jax.tree_util.tree_leaves(grads):
        total = total + jnp.sum(leaf).astype(jnp.float32)
    return jnp.isfinite(total)


def select(flag, new_tree, old_tree):
    """Per-leaf ``where(flag, new, old)`` — the device-side skip mask."""
    return jax.tree_util.tree_map(
        lambda n, o: jnp.where(flag, n, o), new_tree, old_tree)


def next_state(policy, stab, finite) -> Dict[str, jax.Array]:
    """Advance the stability state by one step's finiteness verdict
    (dynamic loss-scale grow/halve, non-finite counter)."""
    fin = finite.astype(jnp.float32)
    scale = stab["loss_scale"]
    streak = stab["growth_streak"]
    if policy.loss_scaling == "dynamic":
        streak = jnp.where(finite, streak + 1.0, 0.0)
        grow = streak >= policy.loss_scale_growth_interval
        scale = jnp.where(
            finite & grow,
            jnp.minimum(scale * policy.loss_scale_factor,
                        policy.loss_scale_max),
            scale)
        streak = jnp.where(grow, 0.0, streak)
        scale = jnp.where(
            finite, scale,
            jnp.maximum(scale / policy.loss_scale_factor,
                        policy.loss_scale_min))
    return {
        "loss_scale": scale,
        "growth_streak": streak,
        "lr_scale": stab["lr_scale"],
        "nonfinite_total": stab["nonfinite_total"] + (1.0 - fin),
    }


def apply_guarded_update(policy, cfg, stab, inner_state, params, net_state,
                         loss, grads, new_ns, iteration, lr_overrides,
                         extra_ok=None):
    """Shared guarded tail of every train step: unscale the gradients,
    take the finiteness verdict, run the updater, and fold the skip into
    the update as a device-side mask.  Returns ``(new_params,
    new_upd_state_with_stability, net_state_out, finite)``.

    ``extra_ok`` lets a caller veto the update with additional device
    evidence (the sync master vetoes a window whose every row was
    poisoned — zero-gradient steps still decay Adam moments)."""
    from deeplearning4j_tpu.optimize import updaters as upd

    grads = {k: v for k, v in grads.items() if v}
    inv = 1.0 / stab["loss_scale"]
    grads = jax.tree_util.tree_map(lambda g: g * inv, grads)
    finite = all_finite(loss, grads)
    if extra_ok is not None:
        finite = finite & extra_ok
    updates, new_inner = upd.update(cfg, grads, inner_state, iteration,
                                    lr_overrides, params=params)
    # the params-tree skip is folded into the update itself: the update
    # becomes EXACTLY 0.0 on a poisoned step, so params - 0 == params
    # bit-for-bit with no second where-pass over the param tree.  A NaN
    # update times 0 would stay NaN, hence where-to-zero BEFORE the
    # scale (XLA fuses both into one elementwise pass).
    lr_scale = stab["lr_scale"]
    if policy.skip_nonfinite:
        scale = jnp.where(finite, lr_scale, 0.0)
        updates = jax.tree_util.tree_map(
            lambda u: jnp.where(finite, u, jnp.zeros_like(u)) * scale,
            updates)
    else:
        updates = jax.tree_util.tree_map(lambda u: u * lr_scale, updates)
    new_params = dict(params)
    for lname, u in updates.items():
        new_params[lname] = upd.apply_updates(params[lname], u)
    if policy.skip_nonfinite:
        new_inner = select(finite, new_inner, inner_state)
        new_ns = select(finite, new_ns, net_state)
    new_inner = dict(new_inner)
    new_inner[STATE_KEY] = next_state(policy, stab, finite)
    return new_params, new_inner, new_ns, finite


def finite_rows(x, y) -> jax.Array:
    """``[B]`` float mask: 1 where every floating element of the
    example's features AND labels is finite (integer leaves — token ids —
    cannot be non-finite and pass).  The sync master folds this into the
    labels mask so poisoned rows renormalize out of the global gradient
    mean exactly like an elastic eviction."""

    def rows_ok(tree):
        ok = None
        for leaf in jax.tree_util.tree_leaves(tree):
            if not jnp.issubdtype(leaf.dtype, jnp.floating):
                continue
            lo = jnp.all(jnp.isfinite(leaf).reshape(leaf.shape[0], -1),
                         axis=1)
            ok = lo if ok is None else ok & lo
        return ok

    ok = rows_ok(x)
    oy = rows_ok(y)
    if ok is None and oy is None:
        leaves = jax.tree_util.tree_leaves(x)
        return jnp.ones((leaves[0].shape[0],), jnp.float32)
    if ok is None:
        ok = oy
    elif oy is not None:
        ok = ok & oy
    return ok.astype(jnp.float32)


def zero_nonfinite_rows(tree, row_ok):
    """Replace poisoned rows of every floating leaf with zeros BEFORE the
    forward pass.  Masking the loss alone is not enough: NaN/Inf
    activations poison the backward pass even under a zero cotangent
    (0 * NaN = NaN), so the poison must never enter the graph."""

    def clean(leaf):
        if not jnp.issubdtype(leaf.dtype, jnp.floating):
            return leaf
        m = row_ok.reshape((leaf.shape[0],) + (1,) * (leaf.ndim - 1))
        return jnp.where(m > 0, leaf, jnp.zeros_like(leaf))

    return jax.tree_util.tree_map(clean, tree)


def slot_poison_flags(row_ok, n_slots: int) -> jax.Array:
    """``[K]`` flags: 1 where ANY row of the slot's contiguous batch
    block is poisoned (the sync master's data layout: slot k owns rows
    ``[k*B/K, (k+1)*B/K)``)."""
    per_slot = row_ok.reshape(n_slots, -1)
    return 1.0 - jnp.min(per_slot, axis=1)


def apply_lr_backoff_tree(upd_state, policy):
    """New updater-state tree with the device-carried LR scale multiplied
    by the backoff factor (pure device op — no host sync; works on the
    facades' scalar state and the wrapper's stacked ``[K]`` state
    alike)."""
    stab = dict(upd_state[STATE_KEY])
    stab["lr_scale"] = stab["lr_scale"] * policy.lr_backoff
    out = dict(upd_state)
    out[STATE_KEY] = stab
    return out


# ---------------------------------------------------------------------------
# host half: boundary harvest, divergence sentinel, escalation
# ---------------------------------------------------------------------------

class StabilityRuntime:
    """Per-component host-side driver (one per facade fit / master).

    The fit loops call ``poll_net`` (facades) or ``accumulate`` +
    ``poll_master`` (parallel masters) once per step/window boundary;
    everything is a no-op except every ``policy.check_every``-th call,
    where the runtime syncs the tiny device scalars it harvests
    (non-finite counter, loss scale, window loss), publishes metrics,
    and runs the divergence sentinel.  Escalation actions:

    - ``"backoff"`` — multiply the device-carried LR scale by
      ``policy.lr_backoff`` (the caller applies it to its live updater
      state via ``apply_lr_backoff_tree``);
    - ``"rewind"`` — restore the newest checkpoint committed while the
      run was still healthy (``rewind``), then back off the LR so the
      rewound run does not immediately re-diverge.
    """

    def __init__(self, component: str, policy, *,
                 worker_ids: Optional[List[str]] = None, registry=None):
        self.component = component
        self.policy = policy
        self.worker_ids = [str(w) for w in (worker_ids or [])]
        if registry is None:
            from deeplearning4j_tpu.observability import get_registry
            registry = get_registry()
        self._m_nonfinite = registry.counter(
            _NONFINITE, "Training steps whose loss or gradients were "
            "non-finite — the device-side guard made them no-ops "
            "(params/updater/net state unchanged); harvested from the "
            "device counter at window boundaries",
            labels=("component",))
        self._m_scale = registry.gauge(
            _LOSS_SCALE, "Current dynamic loss scale of the stability "
            "engine (1 when loss scaling is off)", labels=("component",))
        self._m_lr_scale = registry.gauge(
            _LR_SCALE, "Divergence-sentinel LR backoff multiplier applied "
            "device-side to every update (1 until the first backoff "
            "escalation)", labels=("component",))
        self._m_backoffs = registry.counter(
            _BACKOFFS, "Divergence-sentinel LR-backoff escalations "
            "(sustained non-finite streak or finite loss spike)",
            labels=("component",))
        self._m_rewinds = registry.counter(
            _REWINDS, "Divergence-sentinel auto-rewinds to the last good "
            "checkpoint (params/updater/RNG/iteration restored; read by "
            "the max_divergence_rewinds health rule)",
            labels=("component",))
        self._m_poisoned = registry.counter(
            _POISONED, "Averaging windows in which the named replica's "
            "gradients were non-finite and it was weighted out of the "
            "window average", labels=("component", "worker"))
        self._calls = 0
        self._checks = 0
        self._harvested_nonfinite = 0.0
        self._harvested_poison: Dict[str, float] = {}
        self._lr_scale_host = 1.0
        self._baseline = collections.deque(maxlen=16)
        self._spike_strikes = 0
        self._level = 0
        self._cooldown_until = -1
        self._last_good_step: Optional[int] = None
        # device-side accumulators (masters feed these via accumulate())
        self._nf_acc = None
        self._poison_acc = None

    def baseline_from(self, stab_state) -> None:
        """Anchor the harvest baseline on an EXISTING device counter — a
        checkpointed ``nonfinite_total`` restored by auto-resume (or an
        earlier fit) is history, not fresh evidence; without this anchor
        the first check of a resumed run would re-publish the whole
        historical count and could trip a spurious escalation.  One
        scalar sync, at fit entry / after a rewind only.  A no-op for
        runtimes fed by ``accumulate`` (the wrapper): their counter
        starts at this process's zero by construction."""
        if stab_state is None or self._nf_acc is not None:
            return
        self._harvested_nonfinite = float(
            np.asarray(stab_state["nonfinite_total"]).reshape(-1)[0])

    # ----------------------------------------------------- device feeding
    def accumulate(self, nonfinite_count=None, poison_flags=None) -> None:
        """Fold one window's device-side verdicts into the runtime's
        device accumulators (pure jnp adds — no sync; the sums are read
        at the next check boundary).  Callers whose non-finite counter
        already lives in a replicated stability state (the sync master)
        pass only ``poison_flags``."""
        if nonfinite_count is not None:
            self._nf_acc = (nonfinite_count if self._nf_acc is None
                            else self._nf_acc + nonfinite_count)
        if poison_flags is not None:
            self._poison_acc = (poison_flags if self._poison_acc is None
                                else self._poison_acc + poison_flags)

    # ----------------------------------------------------------- polling
    def poll_net(self, net, res=None) -> Optional[str]:
        """Facade boundary duty: harvest + sentinel every ``check_every``
        steps; applies backoff/rewind to the facade in place.  Returns
        the action taken (telemetry/testing convenience)."""
        self._calls += 1
        if self._calls % self.policy.check_every:
            return None
        stab = net.updater_state.get(STATE_KEY)
        if stab is None:
            return None
        # the ONLY host syncs in the engine: a handful of scalars, once
        # per check window, on values whose compute has already retired
        nonfinite_total = float(np.asarray(stab["nonfinite_total"]))
        self._lr_scale_host = float(np.asarray(stab["lr_scale"]))
        loss = net.score_value
        self._publish(nonfinite_total, float(np.asarray(stab["loss_scale"])))
        action = self._verdict(int(net.iteration), loss,
                               nonfinite_total - self._harvested_nonfinite)
        self._harvested_nonfinite = nonfinite_total
        if action == "backoff":
            net.updater_state = apply_lr_backoff_tree(
                net.updater_state, self.policy)
            self._record_backoff(int(net.iteration))
        elif action == "rewind":
            cm = res.cm if res is not None else None
            if cm is None or self.rewind(net, cm) is None:
                # no checkpoint manager / nothing restorable: the best
                # remaining lever is a (further) LR backoff
                net.updater_state = apply_lr_backoff_tree(
                    net.updater_state, self.policy)
                self._record_backoff(int(net.iteration))
                action = "backoff"
        return action

    def flush(self, net=None, stab_state=None) -> None:
        """Final harvest at fit exit: publish whatever the device counter
        accumulated since the last check boundary (no sentinel verdict —
        the run is over; early stopping and health rules read the
        metrics)."""
        if stab_state is None and net is not None:
            stab_state = net.updater_state.get(STATE_KEY)
        nonfinite_total = None
        scale = 1.0
        if self._nf_acc is not None:
            nonfinite_total = float(np.asarray(self._nf_acc))
        if stab_state is not None:
            if nonfinite_total is None:
                nonfinite_total = float(
                    np.asarray(stab_state["nonfinite_total"]).reshape(-1)[0])
            scale = float(np.asarray(stab_state["loss_scale"]).reshape(-1)[0])
            self._lr_scale_host = float(
                np.asarray(stab_state["lr_scale"]).reshape(-1)[0])
        if nonfinite_total is None:
            return
        self._publish(nonfinite_total, scale)
        self._harvested_nonfinite = nonfinite_total
        self._harvest_poison(int(getattr(net, "iteration", 0) or 0), None)

    def poll_master(self, *, step: int, losses=None, stab_state=None,
                    elastic=None, can_rewind: bool = True) -> Optional[str]:
        """Master boundary duty: harvest the device accumulators (and/or
        the replicated stability state), publish per-replica poison
        verdicts, run the sentinel.  Returns ``None`` | ``"backoff"`` |
        ``"rewind"`` — the caller owns the live device trees and applies
        the action itself.  ``can_rewind=False`` (no checkpoint manager)
        downgrades a rewind verdict to a further backoff, mirroring
        ``poll_net``'s fallback — otherwise an unrewindable run would
        discard every escalation after the first."""
        self._calls += 1
        if self._calls % self.policy.check_every:
            return None
        nonfinite_total = self._harvested_nonfinite
        if self._nf_acc is not None:
            nonfinite_total = float(np.asarray(self._nf_acc))
        elif stab_state is not None:
            nonfinite_total = float(np.asarray(stab_state["nonfinite_total"]))
        scale = 1.0
        if stab_state is not None:
            scale = float(np.asarray(stab_state["loss_scale"]).reshape(-1)[0])
            self._lr_scale_host = float(
                np.asarray(stab_state["lr_scale"]).reshape(-1)[0])
        self._publish(nonfinite_total, scale)
        self._harvest_poison(step, elastic)
        loss = None
        if losses is not None:
            arr = np.asarray(losses, np.float64)
            # poisoned replicas report NaN losses; judge the healthy ones
            loss = (float(np.nanmean(arr))
                    if np.isfinite(arr).any() else float("nan"))
        action = self._verdict(step, loss,
                               nonfinite_total - self._harvested_nonfinite)
        self._harvested_nonfinite = nonfinite_total
        if action == "rewind" and not can_rewind:
            action = "backoff"
        if action == "backoff":
            self._record_backoff(step)
        return action

    def _harvest_poison(self, step: int, elastic) -> None:
        if self._poison_acc is None or not self.worker_ids:
            return
        counts = np.asarray(self._poison_acc, np.float64).reshape(-1)
        for k, worker in enumerate(self.worker_ids):
            total = float(counts[k]) if k < len(counts) else 0.0
            prev = self._harvested_poison.get(worker, 0.0)
            # only a count that ADVANCED since the last check is evidence:
            # a re-admitted replica must not be re-evicted on its old
            # cumulative total
            if total <= prev:
                continue
            self._m_poisoned.inc(total - prev, component=self.component,
                                 worker=worker)
            from deeplearning4j_tpu.observability import (
                get_flight_recorder,
            )
            get_flight_recorder().record(
                "replica_poisoned", component=self.component,
                worker=worker, windows=int(total), step=int(step))
            self._harvested_poison[worker] = total
            if (elastic is not None
                    and total >= self.policy.poison_evict_after):
                elastic.report_poisoned(worker, step)

    # ------------------------------------------------------ sentinel core
    def _publish(self, nonfinite_total: float, loss_scale: float) -> None:
        delta = nonfinite_total - self._harvested_nonfinite
        if delta > 0:
            self._m_nonfinite.inc(delta, component=self.component)
            from deeplearning4j_tpu.observability import get_flight_recorder
            get_flight_recorder().record(
                "nonfinite_steps", component=self.component,
                count=int(delta), total=int(nonfinite_total))
        self._m_scale.set(loss_scale, component=self.component)
        self._m_lr_scale.set(self._lr_scale_host, component=self.component)

    def _verdict(self, step: int, loss: Optional[float],
                 nf_delta: float) -> Optional[str]:
        """Escalation decision for one check window."""
        self._checks += 1
        sustained_nf = nf_delta >= self.policy.nonfinite_streak
        spike = False
        healthy_loss = (loss is not None and math.isfinite(loss))
        if healthy_loss:
            base = (sorted(self._baseline)[len(self._baseline) // 2]
                    if self._baseline else None)
            if (base is not None
                    and loss > self.policy.spike_factor * abs(base) + 1e-6):
                self._spike_strikes += 1
                spike = self._spike_strikes >= self.policy.spike_patience
            else:
                self._spike_strikes = 0
                self._baseline.append(loss)
        if not (sustained_nf or spike):
            if nf_delta == 0 and (loss is None or healthy_loss) \
                    and self._spike_strikes == 0:
                self._last_good_step = step
                self._level = 0
            return None
        if self._checks <= self._cooldown_until:
            return None
        self._level += 1
        return "backoff" if self._level == 1 else "rewind"

    def _record_backoff(self, step: int) -> None:
        from deeplearning4j_tpu.observability import get_flight_recorder

        self._lr_scale_host *= self.policy.lr_backoff
        self._m_backoffs.inc(component=self.component)
        self._m_lr_scale.set(self._lr_scale_host, component=self.component)
        get_flight_recorder().record(
            "divergence_backoff", component=self.component, step=int(step),
            lr_scale=self._lr_scale_host)
        self._cooldown_until = self._checks + 1

    # ------------------------------------------------------------ rewind
    def rewind(self, net, cm, *, mesh=None) -> Optional[int]:
        """Restore the newest checkpoint committed while the run was
        still healthy (falling back to the oldest committed snapshot when
        the whole retention window post-dates the divergence), apply an
        LR backoff so the rewound run does not re-diverge into the same
        wall, and re-arm the sentinel.  Returns the restored step, or
        None when nothing was restorable."""
        from deeplearning4j_tpu.observability import get_flight_recorder

        from_step = int(getattr(net, "iteration", 0))
        steps = cm.all_steps()
        good = [s for s in steps
                if self._last_good_step is None or s <= self._last_good_step]
        # newest snapshot from the healthy era first; if the whole
        # retention window post-dates the divergence, oldest-first is the
        # least-diverged state still on disk
        candidates = [max(good)] if good else []
        candidates += [s for s in sorted(steps) if s not in candidates]
        restored = None
        for target in candidates:
            try:
                cm.restore(net, step=target, mesh=mesh)
                restored = target
                break
            except (FileNotFoundError, OSError):
                continue
        if restored is None:
            get_flight_recorder().record(
                "divergence_rewind_unavailable", component=self.component,
                step=from_step)
            return None
        ensure_state(net)
        net.updater_state = apply_lr_backoff_tree(net.updater_state,
                                                  self.policy)
        # the restored counter is OLDER than the last harvest; re-anchor
        # so post-rewind deltas measure post-rewind evidence only
        self.baseline_from(net.updater_state.get(STATE_KEY))
        self._lr_scale_host *= self.policy.lr_backoff
        self._m_rewinds.inc(component=self.component)
        self._m_lr_scale.set(self._lr_scale_host, component=self.component)
        get_flight_recorder().record(
            "divergence_rewind", component=self.component,
            from_step=from_step, to_step=int(net.iteration))
        self._level = 0
        self._spike_strikes = 0
        self._cooldown_until = self._checks + self.policy.rewind_cooldown_checks
        self._baseline.clear()
        return restored
