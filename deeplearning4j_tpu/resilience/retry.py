"""Step retry with exponential backoff + deterministic jitter.

The reference stack survives a lost executor by letting Spark re-dispatch
the partition (SparkNet §3); a single-controller jax_graft run has no
re-dispatcher, so the fit loops carry their own: a ``RetryPolicy`` wraps
each step dispatch, classifies the exception (transient infrastructure
hiccup vs deterministic model bug), and re-runs transient failures after an
exponential backoff with seeded jitter.  Fatal errors — shape errors, NaN
guards, programming bugs — re-raise immediately: retrying a deterministic
failure just burns the backoff budget and buries the real traceback.

Every retry lands in ``dl4j_step_retries_total{component}`` and the flight
recorder (``retry`` events), so a run that is limping on retries is visible
on /metrics long before it exhausts the budget
(``dl4j_retry_exhausted_total``).
"""

from __future__ import annotations

import logging
import random
import time
from typing import Any, Callable, Optional, Tuple

_RETRIES = "dl4j_step_retries_total"
_EXHAUSTED = "dl4j_retry_exhausted_total"

logger = logging.getLogger("deeplearning4j_tpu.resilience")


class TransientError(RuntimeError):
    """Raise (or subclass) to mark an error as retryable regardless of its
    message."""


# Status substrings that mark an infrastructure error as transient.  The
# gRPC-style codes are what jaxlib's XlaRuntimeError carries when a TPU
# runtime call fails mid-run (preempted host, briefly unreachable
# coordinator, HBM pressure that a retry after backoff may clear).
_TRANSIENT_PATTERNS: Tuple[str, ...] = (
    "resource_exhausted", "unavailable", "deadline_exceeded", "aborted",
    "cancelled", "connection reset", "connection refused", "broken pipe",
    "socket closed", "temporarily unavailable", "transport closed",
    "failed to connect",
)

_TRANSIENT_TYPES = (TransientError, ConnectionError, TimeoutError)

# Never retried: interpreter shutdown, user interrupt, OOM of the host
# process, and the classic deterministic-bug types.
_FATAL_TYPES = (KeyboardInterrupt, SystemExit, GeneratorExit, MemoryError,
                ValueError, TypeError, KeyError, IndexError, AssertionError,
                NotImplementedError)


def is_transient(exc: BaseException) -> bool:
    """Transient vs fatal classification (see module docstring)."""
    if isinstance(exc, _TRANSIENT_TYPES):
        return True
    if isinstance(exc, _FATAL_TYPES):
        return False
    msg = f"{type(exc).__name__}: {exc}".lower()
    return any(p in msg for p in _TRANSIENT_PATTERNS)


class RetryPolicy:
    """Exponential-backoff-with-jitter retry for one component's steps.

    ``delay(attempt) = min(max_delay, base * multiplier**attempt)``, scaled
    by a seeded jitter factor in ``[1 - jitter, 1 + jitter]`` — seeded so a
    test (or a post-mortem replay) sees the exact same backoff schedule.

    ``run(fn)`` executes ``fn`` and retries transient failures up to
    ``max_retries`` times; fatal failures and exhausted budgets re-raise
    the original exception.
    """

    def __init__(self, max_retries: int = 3, base_delay_s: float = 0.5,
                 max_delay_s: float = 30.0, multiplier: float = 2.0,
                 jitter: float = 0.25, seed: int = 0,
                 component: str = "fit",
                 classify: Optional[Callable[[BaseException], bool]] = None,
                 sleep: Callable[[float], None] = time.sleep,
                 registry=None):
        if max_retries < 0:
            raise ValueError(f"max_retries must be >= 0, got {max_retries}")
        if not 0.0 <= jitter <= 1.0:
            raise ValueError(f"jitter must be in [0, 1], got {jitter}")
        self.max_retries = int(max_retries)
        self.base_delay_s = float(base_delay_s)
        self.max_delay_s = float(max_delay_s)
        self.multiplier = float(multiplier)
        self.jitter = float(jitter)
        self.component = component
        self.classify = classify or is_transient
        self._sleep = sleep
        self._rng = random.Random(seed)
        self._registry = registry
        self.retries = 0            # total retries over this policy's life

    def _reg(self):
        if self._registry is not None:
            return self._registry
        from deeplearning4j_tpu.observability import get_registry

        return get_registry()

    def delay(self, attempt: int) -> float:
        """Backoff before retry ``attempt`` (0-based), jitter applied."""
        d = min(self.max_delay_s,
                self.base_delay_s * (self.multiplier ** attempt))
        if self.jitter:
            d *= 1.0 + self.jitter * (2.0 * self._rng.random() - 1.0)
        return max(0.0, d)

    def run(self, fn: Callable[[], Any], *, description: str = "step",
            context: Optional[dict] = None) -> Any:
        """Execute ``fn()`` with transient-failure retries."""
        from deeplearning4j_tpu.observability import get_flight_recorder

        attempt = 0
        while True:
            try:
                return fn()
            except BaseException as e:
                transient = self.classify(e)
                if not transient:
                    raise
                if attempt >= self.max_retries:
                    self._reg().counter(
                        _EXHAUSTED, "Transient step failures that exhausted "
                        "their retry budget and re-raised",
                        labels=("component",)).inc(component=self.component)
                    get_flight_recorder().record(
                        "retry_exhausted", component=self.component,
                        description=description, attempts=attempt,
                        error=repr(e), **(context or {}))
                    raise
                d = self.delay(attempt)
                attempt += 1
                self.retries += 1
                self._reg().counter(
                    _RETRIES, "Step retries after a transient failure "
                    "(exponential backoff with seeded jitter)",
                    labels=("component",)).inc(component=self.component)
                get_flight_recorder().record(
                    "retry", component=self.component,
                    description=description, attempt=attempt,
                    backoff_s=round(d, 4), error=repr(e), **(context or {}))
                logger.warning(
                    "transient failure in %s %s (attempt %d/%d, backing off "
                    "%.2fs): %r", self.component, description, attempt,
                    self.max_retries, d, e)
                if d:
                    self._sleep(d)
