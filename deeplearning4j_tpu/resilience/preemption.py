"""Preemption handling: SIGTERM/SIGINT -> priority checkpoint -> clean stop.

TPU VMs (and any spot/preemptible fleet) get a termination notice as
SIGTERM with a short grace window.  The reference stack rode out executor
loss via Spark lineage; a single-controller run must instead treat the
signal as "checkpoint NOW and exit cleanly": the ``PreemptionHandler``
watches the signals, requests a priority save from its
``CheckpointManager``, and raises a flag every fit loop checks at its next
step boundary (all the loops in this codebase poll
``preemption_requested()`` once per step — a module-global read).

Signal-handler discipline: the handler itself only sets plain flags (no
locks, no IO — a signal can interrupt the main thread while it holds the
very metrics lock a counter increment would need).  The metrics/flight
bookkeeping happens on the fit-loop thread when the stop is first noticed.
The SECOND signal restores the previous disposition first, so a stuck
drain can still be killed the ordinary way.
"""

from __future__ import annotations

import logging
import signal
import threading
from typing import Any, Dict, List, Optional

_PREEMPTS = "dl4j_preemptions_total"

logger = logging.getLogger("deeplearning4j_tpu.resilience")


class PreemptionHandler:
    """SIGTERM/SIGINT watcher driving checkpoint-then-stop (see module
    docstring).

    Usage::

        cm = CheckpointManager("ckpts", save_every_steps=100)
        with PreemptionHandler(cm).install() as ph:
            net.fit(iterator, checkpoint_manager=cm)
        if ph.stop_requested:      # fit stopped early at a step boundary
            ...                    # with a priority checkpoint committed

    ``trigger()`` simulates the signal without OS delivery (worker threads,
    tests of non-main-thread fits).  Installation outside the main thread
    degrades to trigger-only mode with a warning instead of failing.
    """

    def __init__(self, checkpoint_manager=None,
                 signals=(signal.SIGTERM, signal.SIGINT), registry=None):
        self.checkpoint_manager = checkpoint_manager
        self.signals = tuple(signals)
        self._registry = registry
        self._stop = threading.Event()
        self._signum: Optional[int] = None
        self._noticed = False
        self._prev: Dict[int, Any] = {}
        self._installed = False

    # ----------------------------------------------------------- signal path
    def _on_signal(self, signum, frame) -> None:
        # flags only — no locks, no allocation-heavy work (see module
        # docstring); everything observable happens in notice()
        self._signum = signum
        self._stop.set()
        if self.checkpoint_manager is not None:
            self.checkpoint_manager.request_priority_save()
        # second signal escalates: restore previous dispositions so the
        # default action (terminate) goes through if the drain hangs
        self._restore()

    def trigger(self, signum: int = signal.SIGTERM) -> None:
        """Simulate signal delivery (tests / non-main-thread fits)."""
        self._on_signal(signum, None)

    # ------------------------------------------------------------- lifecycle
    def install(self) -> "PreemptionHandler":
        """Register the handlers and make this the process-wide handler the
        fit loops poll."""
        global _active
        try:
            for s in self.signals:
                self._prev[s] = signal.signal(s, self._on_signal)
            self._installed = True
        except ValueError:
            # signal.signal only works on the main thread; degrade to
            # trigger-only mode so worker-thread fits still get the polling
            self._prev.clear()
            logger.warning(
                "PreemptionHandler.install: not on the main thread — OS "
                "signals not hooked, use trigger() to request a stop")
        _active = self
        return self

    def _restore(self) -> None:
        for s, prev in list(self._prev.items()):
            try:
                signal.signal(s, prev)
            except (ValueError, TypeError):
                pass
        self._prev.clear()
        self._installed = False

    def uninstall(self) -> None:
        global _active
        self._restore()
        if _active is self:
            _active = None

    def __enter__(self) -> "PreemptionHandler":
        return self if (_active is self) else self.install()

    def __exit__(self, *exc) -> bool:
        self.uninstall()
        return False

    # -------------------------------------------------------------- queries
    @property
    def stop_requested(self) -> bool:
        return self._stop.is_set()

    @property
    def signal_received(self) -> Optional[int]:
        return self._signum

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._stop.wait(timeout)

    def notice(self) -> None:
        """Called by the fit loop that observes the stop: does the
        bookkeeping the signal handler could not (metrics + flight event),
        exactly once."""
        if self._noticed or not self._stop.is_set():
            return
        self._noticed = True
        signum = self._signum
        name = (signal.Signals(signum).name
                if signum is not None else "manual")
        try:
            from deeplearning4j_tpu.observability import (
                get_flight_recorder, get_registry,
            )

            reg = (self._registry if self._registry is not None
                   else get_registry())
            reg.counter(
                _PREEMPTS, "Preemption signals observed by the fit loops "
                "(SIGTERM/SIGINT -> priority checkpoint + clean stop)",
                labels=("signal",)).inc(signal=name)
            get_flight_recorder().record("preempt", signal=name)
        except Exception:   # bookkeeping must never break the drain
            pass
        logger.warning("preemption (%s): stopping fit at the next step "
                       "boundary", name)

    def reset(self) -> None:
        """Re-arm after a handled stop (long-lived trainer loops).  The
        first signal restored the previous OS dispositions (the
        second-signal escalation path), so re-hook them too — otherwise
        the next preemption would take the default action with no
        checkpoint."""
        had_signal = self._signum is not None
        self._stop.clear()
        self._signum = None
        self._noticed = False
        if had_signal and _active is self and not self._prev:
            try:
                for s in self.signals:
                    self._prev[s] = signal.signal(s, self._on_signal)
                self._installed = True
            except ValueError:
                pass    # non-main thread: stays trigger-only


_active: Optional[PreemptionHandler] = None


def get_preemption_handler() -> Optional[PreemptionHandler]:
    """The installed handler, or None (module-global read — the fit loops
    call this once per step)."""
    return _active


def preemption_requested() -> bool:
    """True when an installed handler has seen its signal.  Fit loops call
    this at every step boundary; when it flips, they commit a priority
    checkpoint (if a manager is wired) and return cleanly."""
    h = _active
    if h is None or not h._stop.is_set():
        return False
    h.notice()
    return True
