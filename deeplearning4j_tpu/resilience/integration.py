"""Fit-loop resilience driver — the one object the training loops talk to.

Every fit loop in the codebase (both facades, the sync master, the
parallel wrapper, the pipeline master) wires resilience the same way, so
the policy lives here once:

1. **auto-resume** on entry: when a ``CheckpointManager`` with
   ``auto_resume=True`` holds a checkpoint AHEAD of the model, restore it
   (params / updater state / RNG stream / iteration) and skip the batches
   the restored run already consumed — the restored run then replays the
   exact step sequence of an uninterrupted one (resume-equivalence is the
   subsystem's test oracle);
2. **per-step scope**: the step dispatch runs inside the fault-injection
   hook and the ``RetryPolicy`` (so an injected or real transient failure
   retries the WHOLE step, injector included);
3. **boundary duties**: after each step, ``maybe_save`` (step/wall-clock/
   priority triggers); before each step, a preemption check — on SIGTERM
   the loop commits a priority checkpoint and returns cleanly.

The loops keep a ``None`` fast path: with no manager and no retry policy
the only added cost is one module-global preemption read per step.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

from deeplearning4j_tpu.resilience.faults import get_fault_injector
from deeplearning4j_tpu.resilience.preemption import preemption_requested


class FitResilience:
    """Per-fit-call resilience state (see module docstring)."""

    def __init__(self, component: str, checkpoint_manager=None,
                 retry_policy=None, *, net=None, mesh=None):
        self.component = component
        self.cm = checkpoint_manager
        self.retry = retry_policy
        self.resumed_from: Optional[int] = None
        self.skip = 0              # batches the restored run already consumed
        self._skipped = 0
        self.stopped = False
        if net is not None and self.cm is not None and self.cm.auto_resume:
            entry = int(getattr(net, "iteration", 0))
            restored = self.cm.resume(net, mesh=mesh)
            if restored is not None:
                self.resumed_from = restored
                self.skip = restored - entry

    # ------------------------------------------------------------ batch gate
    def skip_batch(self) -> bool:
        """True while replaying past batches a resumed checkpoint already
        covers (call once per batch, before any compute)."""
        if self._skipped < self.skip:
            self._skipped += 1
            return True
        return False

    def skip_window(self, steps: int) -> bool:
        """Multi-iteration skip for a batch/window that advances the
        iteration by ``steps`` (ParallelWrapper averaging windows,
        ``num_iterations > 1``, TBPTT windows-per-batch).  Skips only when
        the whole unit is covered — checkpoints are taken at batch/window
        boundaries, so on the same batch stream the remaining skip is
        always either 0 or >= ``steps``."""
        remaining = self.skip - self._skipped
        if remaining >= steps > 0:
            self._skipped += steps
            return True
        return False

    def should_stop(self) -> bool:
        return self.stopped or preemption_requested()

    # -------------------------------------------------------------- the step
    def step(self, fn: Callable[[], Any], iteration: int, net=None) -> Any:
        """Run one step dispatch under fault injection + retry.

        With ``net`` given, the facade's RNG root key is snapshotted before
        the first attempt and rewound before every retry — a retried step
        replays the exact key an uninterrupted run would have used, so
        retries never fork the RNG stream (resume-equivalence depends on
        this)."""
        keys = getattr(net, "_keys", None) if net is not None else None
        saved_key = keys._key if keys is not None else None

        def run():
            if keys is not None:
                keys._key = saved_key
            inj = get_fault_injector()
            if inj is not None:
                inj.on_step(self.component, iteration)
            return fn()

        if self.retry is None:
            return run()
        return self.retry.run(run, description=f"{self.component} step",
                              context={"iteration": iteration})

    def after_step(self, net) -> None:
        if self.cm is not None:
            self.cm.maybe_save(net)

    # --------------------------------------------------------------- stopping
    def on_preempt(self, net) -> None:
        """Commit a priority checkpoint (blocking — the process may be
        about to die) and mark the fit stopped."""
        self.stopped = True
        if self.cm is not None:
            self.cm.save_if_stale(net, trigger="preempt", block=True)
