"""Resilience subsystem: checkpointing, preemption, retry, fault injection.

The reference stack survives worker loss via Spark lineage and re-dispatch
(SparkNet §3, DeepSpark §3.2); the TPU-native port replaces that with a
single-controller fault-tolerance layer (docs/resilience.md):

- ``CheckpointManager`` — async snapshots with atomic commit (tmp ->
  fsync -> rename + COMMIT manifest), keep-N + archival retention, and
  torn-snapshot-proof ``latest()`` discovery;
- ``PreemptionHandler`` — SIGTERM/SIGINT -> priority checkpoint -> clean
  fit-loop stop at the next step boundary;
- ``RetryPolicy`` — exponential-backoff-with-jitter step retry with
  transient/fatal classification;
- ``FaultInjector`` — the seeded deterministic chaos harness the tests
  drive the real paths with (fail a step, crash the checkpoint writer
  between files, corrupt a committed snapshot, slow a worker);
- ``FitResilience`` — the per-fit-call driver the training loops embed
  (auto-resume + skip, per-step retry scope, boundary save/stop duties);
- ``stability`` — the training-stability engine (device-side non-finite
  step guard, dynamic loss scaling, divergence sentinel with LR backoff
  and checkpoint auto-rewind, per-replica poison masking — docs/
  resilience.md "Stability").
"""

from deeplearning4j_tpu.resilience.checkpoint_manager import (
    CheckpointError, CheckpointManager,
)
from deeplearning4j_tpu.resilience.faults import (
    FaultInjector, InjectedFault, TransientInjectedFault,
    get_fault_injector, inject_faults, set_fault_injector,
)
from deeplearning4j_tpu.resilience.integration import FitResilience
from deeplearning4j_tpu.resilience.preemption import (
    PreemptionHandler, get_preemption_handler, preemption_requested,
)
from deeplearning4j_tpu.resilience.retry import (
    RetryPolicy, TransientError, is_transient,
)
from deeplearning4j_tpu.resilience.stability import StabilityRuntime

__all__ = [
    "StabilityRuntime",
    "CheckpointError", "CheckpointManager",
    "FaultInjector", "InjectedFault", "TransientInjectedFault",
    "get_fault_injector", "inject_faults", "set_fault_injector",
    "FitResilience",
    "PreemptionHandler", "get_preemption_handler", "preemption_requested",
    "RetryPolicy", "TransientError", "is_transient",
]
