from deeplearning4j_tpu.streaming.serde import (
    BadRecordError, array_to_base64, base64_to_array, consume_dataset_json,
    dataset_to_json, dataset_from_json, record_to_dataset,
)
from deeplearning4j_tpu.streaming.pubsub import (
    MessageBroker, NDArrayPublisher, NDArrayConsumer,
)
from deeplearning4j_tpu.streaming.serving import (
    InferenceServer, StreamingPipeline, ServingPipeline,
)
