"""Model serving + streaming pipelines (front-ends over ``serving/``).

Reference: ``dl4j-streaming/.../routes/DL4jServeRouteBuilder.java`` (serve a
trained model: consume records, predict, publish predictions back) and
``pipeline/spark/SparkStreamingPipeline.java`` (Kafka -> record conversion ->
DStream<DataSet> -> fit).  TPU redesign: both serving front-ends here (the
HTTP ``InferenceServer`` and the broker-based ``ServingPipeline``) delegate
to ``deeplearning4j_tpu.serving.ServingEngine`` — shape-bucketed dynamic
batching, AOT bucket warmup, versioned hot-swap, and admission control
(docs/serving.md) — instead of the reference's per-message route.
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.observability.health import (
    HealthEvaluator, HealthRule, default_serving_rules,
)
from deeplearning4j_tpu.observability.tracing import new_trace_id
from deeplearning4j_tpu.serving import (
    ServingEngine, ServingError, ShuttingDownError,
)

logger = logging.getLogger("deeplearning4j_tpu.streaming")
access_logger = logging.getLogger("deeplearning4j_tpu.serving.access")
from deeplearning4j_tpu.streaming.pubsub import MessageBroker
from deeplearning4j_tpu.streaming.serde import (
    array_to_base64, base64_to_array, record_to_dataset,
)


class InferenceServer:
    """HTTP front-end over a ``ServingEngine``.

    Endpoints:

    - ``POST /predict`` — NDArray envelope or plain JSON list body; the
      request joins the engine's bucketed micro-batches.  Malformed
      bodies get a structured 400; shed requests 429; shutdown 503;
      deadline expiry 504; model errors 400.
    - ``GET /healthz`` — LIVENESS: cheap dispatcher-thread check, 503
      only when it is dead (a busy-but-working instance must not get
      restarted; no SLO rules evaluated on this path).
    - ``GET /health`` — READINESS/alerting: the full SLO verdict, every
      rule with its observed value, limit, and pass/fail; 503 when any
      rule fails.  Rules default to dispatcher liveness + queue-depth +
      recompile budget; pass ``health_rules=`` for custom SLOs
      (``observability.health.HealthRule``).
    - ``GET /metrics`` — Prometheus scrape of the metrics registry.
    - ``GET /models`` — engine/model-registry state (versions, queue).
    - ``GET /generation/cache`` (when a ``generation=`` engine is
      wired) — paged-pool occupancy plus the persistent prefix cache's
      stats: hit rate, resident/pinned pages, host-tier bytes,
      offload/restore/eviction counters (``null`` under the legacy
      free-on-release policy); 404 without a generation engine.
    - ``POST /models/<name>`` — hot-swap: body ``{"path": <checkpoint>}``
      loads a ``models/serialization.py`` zip, warms every bucket shape,
      and atomically swaps it in with zero dropped requests.
    - ``POST /generate`` (when a ``generation=`` engine is wired) —
      continuous-batching autoregressive decode: body ``{"prompt":
      [ids], "max_tokens": n, "temperature": t, "top_k": k, "top_p": p,
      "seed": s, "stop_token": id, "stream": bool}``.  Without
      ``stream`` the full completion returns as ``{"tokens": [...],
      "finish_reason": ..., "ttft_ms": ..., "trace_id": ...}``; with
      ``stream: true`` the response is Server-Sent Events — one ``data:
      {"token": id, "index": i}`` event per generated token as the
      running decode batch produces it, closed by ``data: {"done":
      true, ...}`` (an error mid-stream becomes a final ``data:
      {"error": ...}`` event: the status line already went out).  Shed/
      deadline mapping (429/503/504), ``X-Request-Id`` trace ids, and
      the access log behave exactly as on ``/predict``.

    Request tracing: every ``/predict`` request gets a ``trace_id`` —
    taken from an ``X-Request-Id`` header when the client sent one,
    minted otherwise — that is propagated through the engine (queue and
    execute spans, shed errors, latency exemplars) and echoed in EVERY
    JSON response body, success or error (429/503/504 included), so a
    client-side timeout can be joined against the server-side spans.
    With ``access_log=True`` one structured JSON line per completed
    request (trace_id, status, bucket, queue_wait_ms, execute_ms) is
    emitted on the ``deeplearning4j_tpu.serving.access`` logger.

    Constructor keeps the PR-1 signature; ``engine=`` supplies a custom
    (possibly shared, multi-model) engine instead.
    """

    def __init__(self, model=None, max_batch: int = 32,
                 max_wait_ms: float = 2.0, port: int = 0, registry=None,
                 max_queue: int = 256, deadline_s: float = 30.0,
                 example: Optional[np.ndarray] = None,
                 engine: Optional[ServingEngine] = None,
                 health_rules=None, access_log: bool = False,
                 generation=None, replica_id: Optional[str] = None):
        if engine is None:
            if model is None:
                raise ValueError("InferenceServer needs a model or an engine")
            engine = ServingEngine(
                model, max_batch=max_batch, max_wait_ms=max_wait_ms,
                max_queue=max_queue, deadline_s=deadline_s,
                registry=registry, example=example)
            self._owns_engine = True
        else:
            if model is not None:
                # the engine serves ITS registered models; silently never
                # serving the passed one would be a trap
                raise ValueError(
                    "pass either model= (server builds its own engine) or "
                    "engine= (serve that engine's models), not both — "
                    "register extra models via engine.deploy()")
            self._owns_engine = False
        self.engine = engine
        # optional generation.GenerationEngine behind POST /generate; its
        # lifecycle (start/stop, deploys) belongs to its owner — the
        # server only routes, exactly like a shared predict engine
        self.generation = generation
        # fleet identity: when set (subprocess replicas behind the fleet
        # router), every /generate envelope, SSE terminal event, and
        # access-log line names the replica that served it — the "which
        # replica did this come from" half of the routing trace
        self.replica_id = replica_id
        self.model = model
        self.max_batch = engine.policy.max_batch
        self.max_wait_ms = engine.batcher.max_wait_s * 1000.0
        self.registry = engine.metrics.registry
        # SLO-driven health: the binary healthz is now a summary of this
        # evaluator's verdict.  The dispatcher-liveness predicate needs
        # the engine object, so it is appended here rather than in
        # default_serving_rules.
        rules = list(health_rules) if health_rules is not None else (
            default_serving_rules(
                max_queue_depth=max(1.0, 0.9 * engine.admission.max_queue)))
        rules.append(HealthRule(
            "dispatcher_alive", "predicate",
            fn=lambda eng: (eng.batcher.is_alive(),
                            eng.batcher.is_alive(),
                            "micro-batch dispatcher thread liveness")))
        self.health = HealthEvaluator(rules, component="serving",
                                      registry=self.registry)
        self.access_log = bool(access_log)
        self._requested_port = port
        self._httpd: Optional[ThreadingHTTPServer] = None

    def predict(self, features: np.ndarray, model: Optional[str] = None,
                deadline_s: Optional[float] = None,
                trace_id: Optional[str] = None) -> np.ndarray:
        """Thread-safe enqueue + bounded wait (usable in-process without
        HTTP).  Raises typed ``ServingError`` subclasses on shed/timeout
        instead of ever hanging the caller."""
        return self.engine.predict(features, model=model,
                                   deadline_s=deadline_s, trace_id=trace_id)

    def _access_line(self, trace_id: str, status: str, http_status: int,
                     model: Optional[str]) -> None:
        """One structured JSON log line per completed /predict request
        (behind ``access_log=``): trace id, outcome, and the per-stage
        breakdown read back from the span tracer."""
        if not self.access_log:
            return
        try:
            br = self.engine.request_breakdown(trace_id)
            access_logger.info(json.dumps({
                "trace_id": trace_id,
                "model": model or self.engine.default_model,
                "status": status,
                "http_status": http_status,
                "bucket": br["bucket"],
                "queue_wait_ms": br["queue_wait_ms"],
                "execute_ms": br["execute_ms"],
                "total_ms": br["total_ms"],
            }))
        except Exception:   # an access-log failure must never 500 a reply
            logger.debug("access-log line failed", exc_info=True)

    def _gen_access_line(self, trace_id: str, status: str, http_status: int,
                         req=None) -> None:
        """The /generate analog of ``_access_line``: same logger, same
        trace-id key, generation-shaped fields (token count, TTFT,
        inter-token p50, SLO verdict — the per-request SLO evidence
        that survives outside the metrics window)."""
        if not self.access_log:
            return
        try:
            access_logger.info(json.dumps({
                "trace_id": trace_id,
                "endpoint": "generate",
                "replica": self.replica_id,
                "status": status,
                "http_status": http_status,
                "tokens": len(req.tokens) if req is not None else None,
                "ttft_ms": (round(req.ttft_s * 1e3, 3)
                            if req is not None and req.ttft_s is not None
                            else None),
                "itl_p50_ms": (req.itl_p50_ms()
                               if req is not None else None),
                "slo_ok": (getattr(req, "slo_ok", None)
                           if req is not None else None),
                "finish_reason": (req.finish_reason
                                  if req is not None else None),
            }))
        except Exception:
            logger.debug("access-log line failed", exc_info=True)

    # ------------------------------------------------------------- lifecycle
    def start(self, warmup: bool = True) -> int:
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _json(self, obj, code=200):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _read_json(self):
                """Parse the request body; raises _BadRequest (-> 400)
                instead of letting a traceback escape as a 500."""
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    return json.loads(self.rfile.read(n).decode())
                except (ValueError, UnicodeDecodeError) as e:
                    raise _BadRequest(f"malformed JSON body: {e}")

            def do_GET(self):
                if self.path == "/healthz":
                    # LIVENESS probe: cheap and binary — fails only on a
                    # dead dispatcher (an instance at its queue budget is
                    # busy, not dead, and restarting busy instances under
                    # load cascades).  Load balancers hit this every few
                    # seconds, so no rule evaluation happens here; the
                    # SLO verdict lives on /health.
                    alive = server.engine.batcher.is_alive()
                    self._json({
                        "status": "ok" if alive else "unavailable",
                        "dispatcher_alive": alive,
                    }, code=200 if alive else 503)
                elif self.path == "/health":
                    # the detailed verdict: every rule with observed vs
                    # limit — the "which SLO is violated" answer
                    verdict = server.health.evaluate(extra=server.engine)
                    self._json(verdict.to_dict(),
                               code=200 if verdict.healthy else 503)
                elif self.path == "/metrics":
                    body = server.registry.to_prometheus().encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type",
                        "text/plain; version=0.0.4; charset=utf-8")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif self.path == "/models":
                    self._json(server.engine.stats())
                elif self.path == "/generation/cache":
                    # paged-pool occupancy + persistent prefix-cache
                    # stats (hit rate, resident/pinned pages, host tier)
                    if server.generation is None:
                        self._json({"error": "this server has no "
                                    "generation engine", "type":
                                    "ModelNotFoundError"}, code=404)
                    else:
                        self._json(server.generation.cache_stats())
                else:
                    self.send_error(404)

            def do_POST(self):
                self._trace_id = None
                try:
                    if self.path == "/predict":
                        self._predict()
                    elif self.path == "/generate":
                        self._generate()
                    elif self.path in ("/generation/pin",
                                       "/generation/unpin"):
                        self._pin(self.path.endswith("/unpin"))
                    elif (self.path.startswith("/models/")
                          and self.path.endswith("/rollback")):
                        self._rollback(
                            self.path[len("/models/"):-len("/rollback")])
                    elif self.path.startswith("/models/"):
                        self._swap(self.path[len("/models/"):])
                    else:
                        self.send_error(404)
                except _BadRequest as e:
                    self._error_json(str(e), type(e).__name__, 400)
                except ServingError as e:
                    self._error_json(str(e), type(e).__name__,
                                     e.http_status,
                                     trace_id=getattr(e, "trace_id", None))
                except Exception as e:  # never drop the socket without a
                    self._error_json(str(e),  # structured response
                                     type(e).__name__, 500)

            def _error_json(self, msg, etype, code, trace_id=None):
                tid = trace_id or self._trace_id
                body = {"error": msg, "type": etype}
                if tid is not None:
                    body["trace_id"] = tid
                    # log BEFORE the response flushes: the client must
                    # never observe a completed request whose access-log
                    # line has not been emitted yet
                    if self.path == "/generate":
                        server._gen_access_line(tid, etype, code, None)
                    else:
                        server._access_line(tid, etype, code, None)
                self._json(body, code=code)

            def _predict(self):
                # trace id from the client when it sent one, minted at
                # the HTTP edge otherwise — the same id rides the engine
                # stages and comes back in the response body
                tid = self.headers.get("X-Request-Id") or new_trace_id()
                self._trace_id = tid
                obj = self._read_json()
                try:
                    if isinstance(obj, dict) and "data" in obj:
                        # validate=True: an undecodable, shape-lying, or
                        # NaN/Inf envelope is a structured 400 here — it
                        # must never reach a forward pass it would share
                        # a micro-batch with other clients' rows in
                        feats = base64_to_array(obj, validate=True)
                    else:
                        feats = np.asarray(obj, np.float32)
                        if not np.isfinite(feats).all():
                            raise _BadRequest(
                                "request payload contains NaN/Inf values")
                except (ValueError, KeyError, TypeError) as e:
                    raise _BadRequest(f"bad request envelope: {e}")
                try:
                    out = server.predict(feats, trace_id=tid)
                except ServingError:
                    raise
                except Exception as e:  # model errors surface as 400s
                    server._access_line(tid, type(e).__name__, 400, None)
                    self._json({"error": str(e), "trace_id": tid}, code=400)
                    return
                # log BEFORE the response flushes (see _error_json)
                server._access_line(tid, "ok", 200, None)
                self._json({**array_to_base64(out), "trace_id": tid})

            def _generate(self):
                """POST /generate — continuous-batching decode.  The
                request joins the RUNNING decode batch at the next step
                boundary; shed/deadline semantics mirror /predict."""
                gen = server.generation
                if gen is None:
                    raise _BadRequest(
                        "this server has no generation engine (pass "
                        "generation= to InferenceServer)")
                tid = self.headers.get("X-Request-Id") or new_trace_id()
                self._trace_id = tid
                obj = self._read_json()
                if not isinstance(obj, dict) or "prompt" not in obj:
                    raise _BadRequest(
                        'generate body must be {"prompt": [token ids], ...}')
                stream = bool(obj.get("stream", False))
                try:
                    prompt = [int(t) for t in obj["prompt"]]
                    req = gen.submit(
                        prompt,
                        max_new_tokens=int(obj.get("max_tokens", 32)),
                        temperature=float(obj.get("temperature", 0.0)),
                        top_k=obj.get("top_k"),
                        top_p=obj.get("top_p"),
                        seed=int(obj.get("seed", 0)),
                        deadline_s=obj.get("deadline_s"),
                        stop_token=obj.get("stop_token"),
                        trace_id=tid)
                except ServingError:
                    raise          # 429/503 mapping via do_POST
                except (TypeError, ValueError) as e:
                    raise _BadRequest(f"bad generate request: {e}")
                if stream:
                    self._stream_tokens(gen, req, tid)
                    return
                try:
                    tokens = req.result()
                except ServingError:
                    raise          # 504 deadline / 503 shutdown mapping
                except Exception as e:   # model/decode failure -> 400
                    server._gen_access_line(tid, type(e).__name__, 400, req)
                    self._json({"error": str(e), "trace_id": tid}, code=400)
                    return
                server._gen_access_line(tid, "ok", 200, req)
                self._json({"tokens": tokens,
                            "finish_reason": req.finish_reason,
                            "ttft_ms": (round(req.ttft_s * 1e3, 3)
                                        if req.ttft_s is not None else None),
                            "trace_id": tid,
                            "replica": server.replica_id})

            def _pin(self, unpin):
                """POST /generation/pin {"prompt": [ids]} -> {"pin_id"}
                and /generation/unpin {"pin_id"} — the HTTP face of
                ``pin_prefix``/``unpin_prefix``, so the fleet router can
                pin sticky sessions on subprocess replicas."""
                gen = server.generation
                if gen is None or getattr(gen, "prefix_cache", None) is None:
                    raise _BadRequest(
                        "this server has no prefix-cache-enabled "
                        "generation engine")
                obj = self._read_json()
                if unpin:
                    if not isinstance(obj, dict) or "pin_id" not in obj:
                        raise _BadRequest('unpin body must be {"pin_id": n}')
                    try:
                        gen.unpin_prefix(int(obj["pin_id"]))
                    except KeyError as e:
                        raise _BadRequest(f"unknown pin: {e}")
                    self._json({"ok": True,
                                "replica": server.replica_id})
                    return
                if not isinstance(obj, dict) or "prompt" not in obj:
                    raise _BadRequest(
                        'pin body must be {"prompt": [token ids]}')
                try:
                    pin_id = gen.pin_prefix([int(t) for t in obj["prompt"]])
                except (TypeError, ValueError) as e:
                    raise _BadRequest(f"bad pin request: {e}")
                self._json({"pin_id": pin_id,
                            "replica": server.replica_id})

            def _stream_tokens(self, gen, req, tid):
                """Server-Sent Events: one event per token as the decode
                batch produces it.  The 200 goes out before the first
                token, so a later failure is delivered as a terminal
                ``data: {"error": ...}`` event instead of a status."""
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Cache-Control", "no-store")
                self.send_header("Connection", "close")
                self.end_headers()

                def event(payload):
                    self.wfile.write(
                        f"data: {json.dumps(payload)}\n\n".encode())
                    self.wfile.flush()

                status, code = "ok", 200
                try:
                    for i, tok in enumerate(req.stream()):
                        event({"token": tok, "index": i, "trace_id": tid})
                    event({"done": True, "tokens": len(req.tokens),
                           "finish_reason": req.finish_reason,
                           "ttft_ms": (round(req.ttft_s * 1e3, 3)
                                       if req.ttft_s is not None else None),
                           "trace_id": tid,
                           "replica": server.replica_id})
                except ServingError as e:
                    status, code = type(e).__name__, e.http_status
                    event({"error": str(e), "type": status,
                           "trace_id": tid, "done": True,
                           "replica": server.replica_id})
                except BrokenPipeError:
                    # client went away: stop wasting decode slots on it
                    req.cancel()
                    status, code = "client_disconnected", 499
                except Exception as e:
                    status, code = type(e).__name__, 500
                    try:
                        event({"error": str(e), "type": status,
                               "trace_id": tid, "done": True})
                    except Exception:
                        pass
                server._gen_access_line(tid, status, code, req)

            def _swap(self, name):
                obj = self._read_json()
                if not isinstance(obj, dict) or "path" not in obj:
                    raise _BadRequest(
                        'hot-swap body must be {"path": <checkpoint>}')
                try:
                    mv = server.engine.deploy(name, obj["path"])
                except Exception as e:
                    # unloadable file, bad zip, or a checkpoint whose
                    # model fails its warmup forward (any exception type)
                    # — the swap aborted and the fault is the artifact's,
                    # so classify as a client error, not a server fault
                    raise _BadRequest(f"cannot deploy checkpoint: {e}")
                self._json({"model": mv.name, "version": mv.version,
                            "state": mv.state})

            def _rollback(self, name):
                """POST /models/<name>/rollback — flip back to the version
                retained by the last retaining hot-swap (the operator's
                manual undo; the online pipeline's watch window calls the
                same engine path automatically)."""
                from deeplearning4j_tpu.serving import ModelNotFoundError

                try:
                    mv = server.engine.rollback(name)
                except ModelNotFoundError as e:
                    raise _BadRequest(str(e))
                self._json({"model": mv.name, "version": mv.version,
                            "state": mv.state})

        if self._owns_engine:
            self.engine.start(warmup=warmup)
        self._httpd = ThreadingHTTPServer(("127.0.0.1", self._requested_port),
                                          Handler)
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self._httpd.server_address[1]

    def stop(self, drain: bool = True):
        if self._owns_engine:
            self.engine.stop(drain=drain)
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if getattr(self, "_thread", None) is not None:
            # shutdown() unblocked serve_forever — bounded join so a
            # stop/start cycle never races the old acceptor thread
            self._thread.join(timeout=5.0)
            self._thread = None


class _BadRequest(ValueError):
    """Client error in the HTTP body; rendered as a structured 400."""


class StreamingPipeline:
    """Consume records from a broker topic, convert to DataSets, train.

    ≙ ``SparkStreamingPipeline.java``: Kafka -> DataVec conversion ->
    fit on each micro-batch.  Records are JSON lists on `topic`; every
    `batch_size` records become one minibatch."""

    def __init__(self, model, broker: MessageBroker, topic: str,
                 label_index: int, num_classes: Optional[int] = None,
                 regression: bool = False, batch_size: int = 32):
        self.model = model
        self.topic = topic
        self.label_index = label_index
        self.num_classes = num_classes
        self.regression = regression
        self.batch_size = batch_size
        self._queue = broker.subscribe(topic)
        self._stop = threading.Event()
        self.batches_trained = 0

    def _drain_batch(self, timeout: float):
        examples = []
        while len(examples) < self.batch_size and not self._stop.is_set():
            try:
                msg = self._queue.get(timeout=timeout)
            except Exception:
                break
            examples.append(record_to_dataset(
                json.loads(msg), self.label_index, self.num_classes,
                self.regression))
        return examples

    def run(self, max_batches: Optional[int] = None, timeout: float = 1.0):
        """Blocking consume-train loop; returns after `max_batches` or when
        the topic stays quiet past `timeout`."""
        while not self._stop.is_set():
            examples = self._drain_batch(timeout)
            if not examples:
                return
            ds = DataSet.merge(examples)
            if len(ds) < self.batch_size:
                ds = ds.pad_batch(self.batch_size)
            self.model.fit(ds.features, ds.labels, lmask=ds.labels_mask)
            self.batches_trained += 1
            if max_batches and self.batches_trained >= max_batches:
                return

    def stop(self):
        self._stop.set()


class ServingPipeline:
    """Consume feature records from `in_topic`, predict, publish predictions
    to `out_topic`.  ≙ ``DL4jServeRouteBuilder.java`` (predictions published
    back to a Kafka topic) — but predictions route through a
    ``ServingEngine``, so concurrent pipelines (or a pipeline plus the HTTP
    server) sharing one engine micro-batch into bucketed forward passes
    instead of paying a per-message ``model.output`` call."""

    def __init__(self, model=None, broker: MessageBroker = None,
                 in_topic: str = "features", out_topic: str = "predictions",
                 transform: Optional[Callable] = None,
                 engine: Optional[ServingEngine] = None,
                 model_name: Optional[str] = None, max_batch: int = 32):
        if broker is None:
            raise ValueError("ServingPipeline needs a broker")
        if engine is None:
            if model is None:
                raise ValueError("ServingPipeline needs a model or an engine")
            engine = ServingEngine(model, max_batch=max_batch)
            self._owns_engine = True
        else:
            self._owns_engine = False
        self.engine = engine
        self.model = model
        self.model_name = model_name
        self.broker = broker
        self.in_topic = in_topic
        self.out_topic = out_topic
        self.transform = transform
        self._queue = broker.subscribe(in_topic)
        self._stop = threading.Event()
        self._engine_started = False
        self._running = False

    def run(self, max_messages: Optional[int] = None, timeout: float = 1.0):
        """Blocking consume-predict-publish loop.  An OWNED engine (no
        ``engine=`` passed) lives only while ``run()`` executes — it is
        started on entry and stopped on exit, so a dropped pipeline never
        leaks the dispatch thread or pins the model; re-warming on a
        later ``run()`` is jit-cache-warm and costs milliseconds.  A
        SHARED engine's lifecycle belongs to its owner and is never
        touched."""
        if self._owns_engine and not self._engine_started:
            self.engine.start()
            self._engine_started = True
        served = 0
        self._running = True
        try:
            while not self._stop.is_set():
                try:
                    msg = self._queue.get(timeout=timeout)
                except Exception:
                    return
                feats = np.asarray(json.loads(msg), np.float32)
                if feats.ndim == 1:
                    feats = feats[None, :]
                if self.transform is not None:
                    feats = self.transform(feats)
                try:
                    out = self.engine.predict(feats, model=self.model_name)
                except ShuttingDownError:
                    return
                except ServingError as e:
                    # transient shed on a SHARED engine (queue burst,
                    # deadline) must not kill the consumer loop
                    logger.warning("dropping message from %r: %s",
                                   self.in_topic, e)
                    continue
                self.broker.publish(self.out_topic,
                                    json.dumps(array_to_base64(out)))
                served += 1
                if max_messages and served >= max_messages:
                    return
        finally:
            self._running = False
            if self._owns_engine:
                self._shutdown_engine()

    def _shutdown_engine(self):
        if self._engine_started:
            self.engine.stop()
            self._engine_started = False

    def stop(self):
        """Stop consuming; also covers the belt-and-braces case of an
        owned engine started but never run."""
        self._stop.set()
        if self._owns_engine and not self._running:
            self._shutdown_engine()
