"""Model serving + streaming pipelines.

Reference: ``dl4j-streaming/.../routes/DL4jServeRouteBuilder.java`` (serve a
trained model: consume records, predict, publish predictions back) and
``pipeline/spark/SparkStreamingPipeline.java`` (Kafka -> record conversion ->
DStream<DataSet> -> fit).  TPU redesign: the serving hot path batches queued
requests before the jitted forward pass so the MXU sees full tiles instead
of single rows, and pads to a fixed max batch so XLA never retraces.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.observability import get_registry
from deeplearning4j_tpu.streaming.pubsub import MessageBroker
from deeplearning4j_tpu.streaming.serde import (
    array_to_base64, base64_to_array, record_to_dataset,
)


import itertools

_SERVER_IDS = itertools.count()


class InferenceServer:
    """HTTP model server: POST /predict with an NDArray envelope (or a plain
    JSON list) returns the model's output.  GET /healthz for liveness,
    GET /metrics for a Prometheus scrape (request counters, latency
    histograms, queue depth — see docs/observability.md).

    Requests that arrive concurrently are micro-batched: the handler thread
    enqueues, a single dispatch thread pads the queue contents to
    ``max_batch`` and runs ONE forward pass — TPU-friendly serving (large
    static-shape batches) replacing the reference's per-message Camel route.
    """

    def __init__(self, model, max_batch: int = 32,
                 max_wait_ms: float = 2.0, port: int = 0, registry=None):
        self.model = model
        self.max_batch = max_batch
        self.max_wait_ms = max_wait_ms
        self._requested_port = port
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._pending: list = []
        self._lock = threading.Condition()
        self._stop = False
        # serving telemetry: scraped live from GET /metrics (Prometheus
        # text format) on this server's own port.  Counters/histograms are
        # additive across instances (unlabeled singletons aggregate
        # naturally); the PER-INSTANCE gauges (queue depth callback, config)
        # are labeled by a process-unique server id so a second server
        # neither clobbers the first's callback nor zeroes it on stop().
        self.registry = registry if registry is not None else get_registry()
        self.server_id = f"s{next(_SERVER_IDS)}"
        self._m_requests = self.registry.counter(
            "dl4j_serving_requests_total",
            "Predict requests by outcome", labels=("status",))
        self._m_latency = self.registry.histogram(
            "dl4j_serving_request_seconds",
            "End-to-end predict latency (enqueue -> response ready, "
            "including micro-batching wait)")
        self._m_rows = self.registry.histogram(
            "dl4j_serving_request_rows",
            "Rows per predict request",
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024))
        self._m_batch_rows = self.registry.histogram(
            "dl4j_serving_batch_rows",
            "Rows per dispatched micro-batch (padding excluded)",
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024))
        # weakref: the registry outlives the server — a strong closure
        # would pin the server (and its model) for process lifetime
        import weakref

        ref = weakref.ref(self)
        self._m_queue = self.registry.gauge(
            "dl4j_serving_queue_depth",
            "Requests waiting for the micro-batch dispatcher",
            labels=("server",)).labels(server=self.server_id)
        self._m_queue.set_function(
            lambda: len(s._pending) if (s := ref()) is not None else 0.0)
        self.registry.gauge(
            "dl4j_serving_max_batch",
            "Configured micro-batch row budget",
            labels=("server",)).set(max_batch, server=self.server_id)

    # --------------------------------------------------------- micro-batcher
    def _run_model(self, feats: np.ndarray) -> np.ndarray:
        """Forward pass in fixed max_batch-shaped chunks: every call XLA
        sees is exactly [max_batch, ...], so no request size ever retraces."""
        outs = []
        for i in range(0, len(feats), self.max_batch):
            chunk = feats[i:i + self.max_batch]
            n = len(chunk)
            if n < self.max_batch:
                pad = np.zeros((self.max_batch - n,) + chunk.shape[1:],
                               chunk.dtype)
                chunk = np.concatenate([chunk, pad])
            outs.append(np.asarray(self.model.output(chunk))[:n])
        return np.concatenate(outs)

    def _dispatch_loop(self):
        while True:
            with self._lock:
                while not self._pending and not self._stop:
                    self._lock.wait(0.1)
                if self._stop:
                    # fail any stragglers instead of hanging their waiters
                    for _f, done, result in self._pending:
                        result.append(RuntimeError("server stopped"))
                        done.set()
                    self._pending.clear()
                    return
                self._lock.wait(self.max_wait_ms / 1000.0)
                # take requests until the row budget is filled (a single
                # oversized request is still taken alone and chunked)
                batch, rows = [], 0
                while self._pending and (not batch
                                         or rows + len(self._pending[0][0])
                                         <= self.max_batch):
                    req = self._pending.pop(0)
                    batch.append(req)
                    rows += len(req[0])
            try:
                feats = np.concatenate([b[0] for b in batch])
                self._m_batch_rows.observe(len(feats))
                out = self._run_model(feats)
                pos = 0
                for f, done, result in batch:
                    result.append(out[pos:pos + len(f)])
                    pos += len(f)
                    done.set()
            except Exception as e:  # deliver the failure to the waiters;
                for _f, done, result in batch:  # the loop must survive
                    result.append(e)
                    done.set()

    def predict(self, features: np.ndarray) -> np.ndarray:
        """Thread-safe enqueue + wait (used by the HTTP handler and usable
        directly in-process)."""
        features = np.asarray(features, np.float32)
        if features.ndim == 1:
            features = features[None, :]
        t0 = time.perf_counter()
        done = threading.Event()
        result: list = []
        with self._lock:
            self._pending.append((features, done, result))
            self._lock.notify_all()
        done.wait()
        self._m_latency.observe(time.perf_counter() - t0)
        self._m_rows.observe(len(features))
        if isinstance(result[0], Exception):
            self._m_requests.inc(status="error")
            raise result[0]
        self._m_requests.inc(status="ok")
        return result[0]

    # ------------------------------------------------------------- lifecycle
    def start(self) -> int:
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _json(self, obj, code=200):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    self._json({"status": "ok"})
                elif self.path == "/metrics":
                    # Prometheus text exposition of the server's registry
                    # (serving metrics + whatever else the process records:
                    # fit metrics, compile counts, device memory…)
                    body = server.registry.to_prometheus().encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type",
                        "text/plain; version=0.0.4; charset=utf-8")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self.send_error(404)

            def do_POST(self):
                if self.path != "/predict":
                    self.send_error(404)
                    return
                n = int(self.headers.get("Content-Length", 0))
                obj = json.loads(self.rfile.read(n).decode())
                if isinstance(obj, dict) and "data" in obj:
                    feats = base64_to_array(obj)
                else:
                    feats = np.asarray(obj, np.float32)
                try:
                    out = server.predict(feats)
                except Exception as e:  # surface model errors as 400s
                    self._json({"error": str(e)}, code=400)
                    return
                self._json(array_to_base64(out))

        self._stop = False
        self._dispatcher = threading.Thread(target=self._dispatch_loop,
                                            daemon=True)
        self._dispatcher.start()
        self._httpd = ThreadingHTTPServer(("127.0.0.1", self._requested_port),
                                          Handler)
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self._httpd.server_address[1]

    def stop(self):
        with self._lock:
            self._stop = True
            self._lock.notify_all()
        # freeze THIS server's queue gauge (per-instance labeled child —
        # other servers' callbacks are untouched)
        self._m_queue.set(0.0)
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None


class StreamingPipeline:
    """Consume records from a broker topic, convert to DataSets, train.

    ≙ ``SparkStreamingPipeline.java``: Kafka -> DataVec conversion ->
    fit on each micro-batch.  Records are JSON lists on `topic`; every
    `batch_size` records become one minibatch."""

    def __init__(self, model, broker: MessageBroker, topic: str,
                 label_index: int, num_classes: Optional[int] = None,
                 regression: bool = False, batch_size: int = 32):
        self.model = model
        self.topic = topic
        self.label_index = label_index
        self.num_classes = num_classes
        self.regression = regression
        self.batch_size = batch_size
        self._queue = broker.subscribe(topic)
        self._stop = threading.Event()
        self.batches_trained = 0

    def _drain_batch(self, timeout: float):
        examples = []
        while len(examples) < self.batch_size and not self._stop.is_set():
            try:
                msg = self._queue.get(timeout=timeout)
            except Exception:
                break
            examples.append(record_to_dataset(
                json.loads(msg), self.label_index, self.num_classes,
                self.regression))
        return examples

    def run(self, max_batches: Optional[int] = None, timeout: float = 1.0):
        """Blocking consume-train loop; returns after `max_batches` or when
        the topic stays quiet past `timeout`."""
        while not self._stop.is_set():
            examples = self._drain_batch(timeout)
            if not examples:
                return
            ds = DataSet.merge(examples)
            if len(ds) < self.batch_size:
                ds = ds.pad_batch(self.batch_size)
            self.model.fit(ds.features, ds.labels, lmask=ds.labels_mask)
            self.batches_trained += 1
            if max_batches and self.batches_trained >= max_batches:
                return

    def stop(self):
        self._stop.set()


class ServingPipeline:
    """Consume feature records from `in_topic`, predict, publish predictions
    to `out_topic`.  ≙ ``DL4jServeRouteBuilder.java`` (predictions published
    back to a Kafka topic)."""

    def __init__(self, model, broker: MessageBroker, in_topic: str,
                 out_topic: str, transform: Optional[Callable] = None):
        self.model = model
        self.broker = broker
        self.in_topic = in_topic
        self.out_topic = out_topic
        self.transform = transform
        self._queue = broker.subscribe(in_topic)
        self._stop = threading.Event()

    def run(self, max_messages: Optional[int] = None, timeout: float = 1.0):
        served = 0
        while not self._stop.is_set():
            try:
                msg = self._queue.get(timeout=timeout)
            except Exception:
                return
            feats = np.asarray(json.loads(msg), np.float32)
            if feats.ndim == 1:
                feats = feats[None, :]
            if self.transform is not None:
                feats = self.transform(feats)
            out = np.asarray(self.model.output(feats))
            self.broker.publish(self.out_topic,
                                json.dumps(array_to_base64(out)))
            served += 1
            if max_messages and served >= max_messages:
                return

    def stop(self):
        self._stop.set()
