"""Topic-based pub/sub message broker + NDArray publisher/consumer.

Reference: the Kafka plumbing in ``dl4j-streaming`` —
``kafka/NDArrayKafkaClient.java`` (broker handle),
``kafka/NDArrayPublisher.java`` (publish base64 arrays to a topic),
``kafka/NDArrayConsumer.java`` (consume them back).  The TPU framework
replaces the Kafka dependency with a self-contained broker: named topics,
bounded per-subscriber queues, thread-safe, with an optional HTTP
transport so producers/consumers can sit in different processes
(the ``UIServer``-style stdlib HTTP stack — no external deps).
"""

from __future__ import annotations

import json
import logging
import queue
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

import numpy as np

from deeplearning4j_tpu.streaming.serde import array_to_base64, base64_to_array

_DROPPED = "dl4j_stream_dropped_total"
_DROP_WARN_INTERVAL_S = 30.0

logger = logging.getLogger("deeplearning4j_tpu.streaming")


class MessageBroker:
    """In-process topic broker; each subscriber gets an independent bounded
    queue (Kafka consumer-group-of-one semantics)."""

    def __init__(self, queue_size: int = 1024, registry=None):
        self._queue_size = queue_size
        self._topics: Dict[str, List[queue.Queue]] = {}
        self._lock = threading.Lock()
        self._registry = registry
        self._last_drop_warn: Dict[str, float] = {}
        self._httpd = None
        self._thread: Optional[threading.Thread] = None

    def subscribe(self, topic: str) -> "queue.Queue[str]":
        q: "queue.Queue[str]" = queue.Queue(maxsize=self._queue_size)
        with self._lock:
            self._topics.setdefault(topic, []).append(q)
        return q

    def unsubscribe(self, topic: str, q: "queue.Queue") -> None:
        with self._lock:
            subs = self._topics.get(topic, [])
            if q in subs:
                subs.remove(q)

    def publish(self, topic: str, message: str) -> int:
        """Deliver to every subscriber.  A full subscriber queue drops its
        OLDEST message (bounded-lag semantics, like a Kafka consumer falling
        behind a retention window) — publish never blocks on a slow or
        abandoned consumer.  Every message discarded this way is counted in
        ``dl4j_stream_dropped_total{topic}`` and surfaced by a rate-limited
        warning naming the topic — silent data loss on a training stream is
        a model-quality bug, not a transport detail."""
        dropped = 0
        with self._lock:
            subs = list(self._topics.get(topic, []))
        for q in subs:
            while True:
                try:
                    q.put_nowait(message)
                    break
                except queue.Full:
                    try:
                        q.get_nowait()
                        dropped += 1
                    except queue.Empty:
                        pass
        if dropped:
            self._count_drops(topic, dropped)
        return len(subs)

    def _count_drops(self, topic: str, n: int) -> None:
        reg = self._registry
        if reg is None:
            from deeplearning4j_tpu.observability import get_registry

            reg = get_registry()
        reg.counter(
            _DROPPED, "Messages discarded because a subscriber queue was "
            "full (oldest-first, bounded-lag semantics) — a consumer "
            "falling behind its topic", labels=("topic",)
        ).inc(n, topic=topic)
        now = time.monotonic()
        with self._lock:
            last = self._last_drop_warn.get(topic)
            warn = last is None or now - last >= _DROP_WARN_INTERVAL_S
            if warn:
                self._last_drop_warn[topic] = now
        if warn:
            logger.warning(
                "topic %r dropped %d message(s): a subscriber queue is full "
                "(queue_size=%d) and the oldest messages were discarded — "
                "see dl4j_stream_dropped_total{topic=%r}",
                topic, n, self._queue_size, topic)

    # ---------------------------------------------------------- HTTP server
    def serve(self, port: int = 0, sub_idle_timeout: float = 300.0) -> int:
        """Expose the broker over HTTP: POST /publish/<topic> (body = message),
        GET /poll/<topic>?sub=<id> long-polls the named subscription.
        Subscriptions idle past `sub_idle_timeout` seconds are dropped so an
        abandoned poller can't accumulate messages forever."""
        import time as _time

        broker = self
        http_subs: Dict[str, list] = {}  # key -> [queue, topic, last_poll]
        lock = threading.Lock()

        def purge():
            now = _time.monotonic()
            with lock:
                for key in [k for k, v in http_subs.items()
                            if now - v[2] > sub_idle_timeout]:
                    q, topic, _ = http_subs.pop(key)
                    broker.unsubscribe(topic, q)

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):
                if not self.path.startswith("/publish/"):
                    self.send_error(404)
                    return
                purge()
                topic = self.path[len("/publish/"):]
                n = int(self.headers.get("Content-Length", 0))
                count = broker.publish(topic, self.rfile.read(n).decode())
                body = json.dumps({"delivered": count}).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path, _, query = self.path.partition("?")
                if not path.startswith("/poll/"):
                    self.send_error(404)
                    return
                purge()  # GET-only clients must also trigger idle cleanup
                topic = path[len("/poll/"):]
                params = dict(p.split("=", 1) for p in query.split("&")
                              if "=" in p)
                key = topic + ":" + params.get("sub", "default")
                with lock:
                    if key not in http_subs:
                        http_subs[key] = [broker.subscribe(topic), topic,
                                          _time.monotonic()]
                    http_subs[key][2] = _time.monotonic()
                    q = http_subs[key][0]
                try:
                    msg = q.get(timeout=float(params.get("timeout", 5.0)))
                    self.send_response(200)
                    body = msg.encode()
                except queue.Empty:
                    self.send_response(204)
                    body = b""
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self._httpd.server_address[1]

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            # bounded join: shutdown() above unblocks serve_forever, so
            # this returns promptly — without it a restart could race the
            # old acceptor thread on the (reused) port
            self._thread.join(timeout=5.0)
            self._thread = None


class NDArrayPublisher:
    """Publishes numpy arrays to a topic (local broker or remote HTTP one).
    ≙ ``NDArrayPublisher.java``."""

    def __init__(self, topic: str, broker: Optional[MessageBroker] = None,
                 url: Optional[str] = None, timeout: float = 5.0):
        if (broker is None) == (url is None):
            raise ValueError("exactly one of broker/url required")
        self.topic = topic
        self.broker = broker
        self.url = url.rstrip("/") if url else None
        self.timeout = timeout

    def publish(self, arr: np.ndarray) -> None:
        msg = json.dumps(array_to_base64(np.asarray(arr)))
        if self.broker is not None:
            self.broker.publish(self.topic, msg)
        else:
            req = urllib.request.Request(
                f"{self.url}/publish/{self.topic}", data=msg.encode(),
                headers={"Content-Type": "application/json"})
            urllib.request.urlopen(req, timeout=self.timeout)


class NDArrayConsumer:
    """Consumes numpy arrays from a topic.  ≙ ``NDArrayConsumer.java``."""

    def __init__(self, topic: str, broker: Optional[MessageBroker] = None,
                 url: Optional[str] = None, sub_id: str = "default",
                 timeout: float = 5.0):
        if (broker is None) == (url is None):
            raise ValueError("exactly one of broker/url required")
        self.topic = topic
        self.url = url.rstrip("/") if url else None
        self.sub_id = sub_id
        self.timeout = timeout
        self._queue = broker.subscribe(topic) if broker is not None else None

    def poll(self, timeout: Optional[float] = None) -> Optional[np.ndarray]:
        timeout = self.timeout if timeout is None else timeout
        if self._queue is not None:
            try:
                msg = self._queue.get(timeout=timeout)
            except queue.Empty:
                return None
        else:
            req = (f"{self.url}/poll/{self.topic}?sub={self.sub_id}"
                   f"&timeout={timeout}")
            with urllib.request.urlopen(req, timeout=timeout + 5) as resp:
                if resp.status == 204:
                    return None
                msg = resp.read().decode()
        return base64_to_array(json.loads(msg))
