"""NDArray / DataSet wire serialization for the streaming layer.

Reference: ``dl4j-streaming/.../serde/RecordSerializer.java`` plus the
base64 NDArray encoding used by ``kafka/NDArrayPublisher.java`` /
``NDArrayConsumer.java`` (arrays travel as base64 strings inside JSON
messages).  Format here: little-endian float32 payload + explicit shape,
JSON-framed, so any consumer can decode without this library.
"""

from __future__ import annotations

import base64
import json
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet


def array_to_base64(arr: np.ndarray) -> Dict[str, Any]:
    """{'shape': [...], 'dtype': 'float32', 'data': <base64>} envelope."""
    arr = np.ascontiguousarray(arr, np.float32)
    return {
        "shape": list(arr.shape),
        "dtype": "float32",
        "data": base64.b64encode(arr.tobytes()).decode("ascii"),
    }


def base64_to_array(env: Dict[str, Any]) -> np.ndarray:
    raw = base64.b64decode(env["data"])
    return np.frombuffer(raw, np.float32).reshape(env["shape"]).copy()


def dataset_to_json(ds: DataSet) -> str:
    obj: Dict[str, Any] = {"features": array_to_base64(ds.features),
                           "labels": array_to_base64(ds.labels)}
    if ds.features_mask is not None:
        obj["features_mask"] = array_to_base64(ds.features_mask)
    if ds.labels_mask is not None:
        obj["labels_mask"] = array_to_base64(ds.labels_mask)
    return json.dumps(obj)


def dataset_from_json(text: str) -> DataSet:
    obj = json.loads(text)
    return DataSet(
        base64_to_array(obj["features"]),
        base64_to_array(obj["labels"]),
        base64_to_array(obj["features_mask"]) if "features_mask" in obj else None,
        base64_to_array(obj["labels_mask"]) if "labels_mask" in obj else None,
    )


def record_to_dataset(record: Sequence[float], label_index: Optional[int],
                      num_classes: Optional[int] = None,
                      regression: bool = False) -> DataSet:
    """Single record -> 1-example DataSet (the record-conversion step of
    ``conversion/dataset/*`` in the reference streaming module)."""
    vals = np.asarray(list(record), np.float32)
    if label_index is None:
        return DataSet(vals[None, :], np.zeros((1, 0), np.float32))
    feat = np.concatenate([vals[:label_index], vals[label_index + 1:]])
    if regression:
        lab = vals[label_index:label_index + 1]
    else:
        if not num_classes:
            raise ValueError("num_classes is required for classification "
                             "records (regression=False)")
        c = int(vals[label_index])
        if not 0 <= c < num_classes:
            raise ValueError(f"label value {c} outside [0, {num_classes})")
        lab = np.zeros(num_classes, np.float32)
        lab[c] = 1.0
    return DataSet(feat[None, :], lab[None, :])
