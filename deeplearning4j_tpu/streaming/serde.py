"""NDArray / DataSet wire serialization for the streaming layer.

Reference: ``dl4j-streaming/.../serde/RecordSerializer.java`` plus the
base64 NDArray encoding used by ``kafka/NDArrayPublisher.java`` /
``NDArrayConsumer.java`` (arrays travel as base64 strings inside JSON
messages).  Format here: little-endian float32 payload + explicit shape,
JSON-framed, so any consumer can decode without this library.

Consume-side validation: anything pulled off a topic that will reach a
``fit`` or ``output`` call can be decoded with ``validate=True`` (or via
``consume_dataset_json``), which rejects undecodable base64, dtype/shape
mismatches, payload-length lies, and NaN/Inf values with a typed
``BadRecordError`` instead of letting a poisoned record corrupt a whole
training window.  ``BadRecordError.reason`` carries a bounded-cardinality
classification (``bad_json`` / ``bad_envelope`` / ``bad_base64`` /
``bad_dtype`` / ``shape_mismatch`` / ``non_finite``) — the quarantine
path labels its metrics with it.
"""

from __future__ import annotations

import base64
import binascii
import json
import math
from typing import Any, Dict, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet


class BadRecordError(ValueError):
    """A malformed stream record — the quarantine (dead-letter) trigger.

    ``reason`` is one of a small fixed set so it is safe as a metric
    label: ``bad_json``, ``bad_envelope``, ``bad_base64``, ``bad_dtype``,
    ``shape_mismatch``, ``non_finite``.
    """

    def __init__(self, message: str, reason: str = "bad_envelope"):
        super().__init__(message)
        self.reason = reason


def array_to_base64(arr: np.ndarray) -> Dict[str, Any]:
    """{'shape': [...], 'dtype': 'float32', 'data': <base64>} envelope."""
    arr = np.ascontiguousarray(arr, np.float32)
    return {
        "shape": list(arr.shape),
        "dtype": "float32",
        "data": base64.b64encode(arr.tobytes()).decode("ascii"),
    }


def base64_to_array(env: Dict[str, Any], validate: bool = False) -> np.ndarray:
    """Decode one NDArray envelope.  With ``validate`` every way a record
    can lie is checked BEFORE the array is returned: envelope keys, dtype,
    shape types, strict base64 (a bit-flipped payload character fails
    instead of being silently skipped), byte length vs shape, and value
    finiteness — each failure raises ``BadRecordError`` with a bounded
    ``reason``."""
    if not validate:
        raw = base64.b64decode(env["data"])
        return np.frombuffer(raw, np.float32).reshape(env["shape"]).copy()
    if not isinstance(env, dict) or "data" not in env or "shape" not in env:
        raise BadRecordError(
            "envelope must be a dict with 'shape' and 'data'",
            reason="bad_envelope")
    dtype = env.get("dtype", "float32")
    if dtype != "float32":
        raise BadRecordError(f"unsupported dtype {dtype!r} (want float32)",
                             reason="bad_dtype")
    shape = env["shape"]
    if (not isinstance(shape, (list, tuple))
            or not all(isinstance(d, int) and not isinstance(d, bool)
                       and d >= 0 for d in shape)):
        raise BadRecordError(f"bad shape {shape!r}", reason="shape_mismatch")
    try:
        # strict alphabet: a corrupted (bit-flipped) character raises here
        # instead of being skipped by the default lenient decoder
        raw = base64.b64decode(env["data"], validate=True)
    except (binascii.Error, TypeError, ValueError) as e:
        raise BadRecordError(f"undecodable base64 payload: {e}",
                             reason="bad_base64")
    expected = int(np.prod(shape, dtype=np.int64)) * 4
    if len(raw) != expected:
        raise BadRecordError(
            f"payload is {len(raw)} bytes but shape {list(shape)} needs "
            f"{expected}", reason="shape_mismatch")
    arr = np.frombuffer(raw, np.float32).reshape(shape).copy()
    if not np.isfinite(arr).all():
        raise BadRecordError("payload contains NaN/Inf values",
                             reason="non_finite")
    return arr


def dataset_to_json(ds: DataSet, meta: Optional[Dict[str, Any]] = None) -> str:
    """Serialize a DataSet message.  ``meta`` rides along verbatim under
    a ``"meta"`` key (e.g. ``{"ts": time.time()}`` — the publish
    timestamp the online pipeline's model-freshness measurement reads);
    consumers that don't know about it ignore it."""
    obj: Dict[str, Any] = {"features": array_to_base64(ds.features),
                           "labels": array_to_base64(ds.labels)}
    if ds.features_mask is not None:
        obj["features_mask"] = array_to_base64(ds.features_mask)
    if ds.labels_mask is not None:
        obj["labels_mask"] = array_to_base64(ds.labels_mask)
    if meta:
        obj["meta"] = meta
    return json.dumps(obj)


def dataset_from_json(text: str, validate: bool = False) -> DataSet:
    ds, _meta = _decode_dataset(text, validate)
    return ds


def consume_dataset_json(text: str) -> Tuple[DataSet, Dict[str, Any]]:
    """The validating consume path: decode one DataSet message, rejecting
    anything malformed with ``BadRecordError`` (see module docstring).
    Returns ``(dataset, meta)`` where ``meta`` is the publisher's
    metadata dict (empty when absent)."""
    return _decode_dataset(text, validate=True)


def _decode_dataset(text: str,
                    validate: bool) -> Tuple[DataSet, Dict[str, Any]]:
    try:
        obj = json.loads(text)
    except (ValueError, TypeError) as e:
        raise BadRecordError(f"record is not JSON: {e}", reason="bad_json")
    if validate and (not isinstance(obj, dict) or "features" not in obj
                     or "labels" not in obj):
        raise BadRecordError(
            "DataSet message must be a dict with 'features' and 'labels'",
            reason="bad_envelope")
    feats = base64_to_array(obj["features"], validate=validate)
    labels = base64_to_array(obj["labels"], validate=validate)
    if validate:
        if feats.ndim == 0 or labels.ndim == 0:
            # a 0-d array has no row axis — len() on it would raise an
            # UNTYPED error downstream instead of quarantining
            raise BadRecordError(
                "scalar (0-d) features/labels have no batch dimension",
                reason="shape_mismatch")
        if len(labels) and len(feats) and len(labels) != len(feats):
            raise BadRecordError(
                f"features have {len(feats)} rows but labels {len(labels)}",
                reason="shape_mismatch")
    fmask = (base64_to_array(obj["features_mask"], validate=validate)
             if "features_mask" in obj else None)
    lmask = (base64_to_array(obj["labels_mask"], validate=validate)
             if "labels_mask" in obj else None)
    if validate:
        for name, mask in (("features_mask", fmask), ("labels_mask", lmask)):
            if mask is None:
                continue
            # a shape-lying mask would crash fit mid-window — same
            # quarantine contract as the features/labels themselves
            if mask.ndim == 0 or len(mask) != len(feats):
                raise BadRecordError(
                    f"{name} has "
                    f"{'no batch dimension' if mask.ndim == 0 else f'{len(mask)} rows'}"
                    f" but features have {len(feats)}",
                    reason="shape_mismatch")
    meta = obj.get("meta") if isinstance(obj, dict) else None
    if not isinstance(meta, dict):
        meta = {}
    if validate:
        ts = meta.get("ts")
        if ts is not None and (not isinstance(ts, (int, float))
                               or isinstance(ts, bool)
                               or not math.isfinite(ts)):
            raise BadRecordError(f"bad meta.ts {ts!r}", reason="bad_envelope")
    return DataSet(feats, labels, fmask, lmask), meta


def record_to_dataset(record: Sequence[float], label_index: Optional[int],
                      num_classes: Optional[int] = None,
                      regression: bool = False) -> DataSet:
    """Single record -> 1-example DataSet (the record-conversion step of
    ``conversion/dataset/*`` in the reference streaming module)."""
    vals = np.asarray(list(record), np.float32)
    if label_index is None:
        return DataSet(vals[None, :], np.zeros((1, 0), np.float32))
    feat = np.concatenate([vals[:label_index], vals[label_index + 1:]])
    if regression:
        lab = vals[label_index:label_index + 1]
    else:
        if not num_classes:
            raise ValueError("num_classes is required for classification "
                             "records (regression=False)")
        c = int(vals[label_index])
        if not 0 <= c < num_classes:
            raise ValueError(f"label value {c} outside [0, {num_classes})")
        lab = np.zeros(num_classes, np.float32)
        lab[c] = 1.0
    return DataSet(feat[None, :], lab[None, :])
