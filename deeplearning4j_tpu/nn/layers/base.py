"""Layer base abstraction — the functional re-design of the reference's
``nn/api/Layer.java`` + one-config-class-per-layer (``nn/conf/layers/*.java``).

A layer here is a *frozen config dataclass* exposing:
  - ``setup(input_type)``  -> completed copy (n_in inferred) — replaces the
    reference's ``ConvolutionLayerSetup``/``InputTypeUtil`` auto-wiring
  - ``output_type(input_type)`` -> static shape inference
  - ``init(key, dtype)``   -> parameter pytree (dict name->array) — replaces
    ``ParamInitializer`` (``nn/params/*.java``)
  - ``init_state()``       -> non-trainable state pytree (e.g. BN running stats)
  - ``apply(params, state, x, *, train, rng)`` -> (y, new_state) — replaces
    ``Layer.activate``; backprop is ``jax.grad`` through apply, replacing the
    reference's hand-written ``backpropGradient`` chains.

There is no mutable layer object holding params: params live in the model's
pytree, so the whole train step jits to one XLA program and shards with pjit.

Serialization: each class registers under its reference-style type name;
``to_dict``/``layer_from_dict`` give the Jackson-subtype-registry equivalent
(custom layers register the same way — ``register_layer``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple, Type

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.inputs import InputType

_LAYER_REGISTRY: Dict[str, Type["Layer"]] = {}


def register_layer(cls: Type["Layer"]) -> Type["Layer"]:
    """Class decorator: register a layer type for JSON round-trip
    (the Jackson ``@JsonSubTypes`` equivalent; custom layers use this too,
    mirroring the reference custom-layer tests ``nn/layers/custom/``)."""
    _LAYER_REGISTRY[cls.__name__] = cls
    return cls


def layer_from_dict(d: Dict[str, Any]) -> "Layer":
    d = dict(d)
    type_name = d.pop("type")
    cls = _LAYER_REGISTRY.get(type_name)
    if cls is None:
        raise ValueError(f"Unknown layer type '{type_name}'; registered: {sorted(_LAYER_REGISTRY)}")
    return cls.from_dict(d)


@dataclasses.dataclass(frozen=True)
class Layer:
    """Base layer config. Fields every layer shares (reference
    ``nn/conf/layers/Layer.java`` base: activation, weightInit, dropOut,
    l1/l2, learning-rate overrides)."""

    name: Optional[str] = None
    activation: str = "sigmoid"
    weight_init: str = "xavier"
    dist: Optional[dict] = None        # distribution spec when weight_init="distribution"
    dropout: float = 0.0               # input dropout probability (reference dropOut)
    drop_connect: bool = False         # dropOut masks WEIGHTS instead of inputs
    _SUPPORTS_DROP_CONNECT = False     # overridden by layers that mask W
    l1: float = 0.0
    l2: float = 0.0
    learning_rate: Optional[float] = None   # per-layer lr override
    bias_init: float = 0.0

    # ---- validation -----------------------------------------------------
    def validate(self) -> None:
        """Fail fast at build time on unknown activation / weight-init names
        (otherwise the error would surface mid-trace at first fit/output)."""
        from deeplearning4j_tpu.nn import activations, initializers

        activations.get(self.activation)
        initializers.check(self.weight_init)
        if self.drop_connect and not self._SUPPORTS_DROP_CONNECT:
            # fail fast: with drop_connect set, input dropout is disabled,
            # so a layer that never masks W would silently lose ALL dropout
            raise ValueError(
                f"{type(self).__name__} does not support drop_connect "
                "(weight masking is implemented for Dense/Output layers); "
                "use plain dropout here")

    # ---- shape plumbing -------------------------------------------------
    def setup(self, input_type: InputType) -> "Layer":
        """Return a completed copy with sizes inferred from input_type."""
        return self

    def output_type(self, input_type: InputType) -> InputType:
        raise NotImplementedError

    # ---- params ---------------------------------------------------------
    def init(self, key: jax.Array, dtype=jnp.float32) -> Dict[str, jax.Array]:
        raise NotImplementedError

    def init_state(self) -> Dict[str, jax.Array]:
        return {}

    def has_params(self) -> bool:
        return True

    # ---- forward --------------------------------------------------------
    def apply(
        self,
        params: Dict[str, jax.Array],
        state: Dict[str, jax.Array],
        x: jax.Array,
        *,
        train: bool = False,
        rng: Optional[jax.Array] = None,
    ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
        raise NotImplementedError

    def maybe_dropout(self, x, *, train, rng):
        """Input dropout (reference ``util/Dropout.java`` applyDropout:
        inverted dropout scaling at train time).  With ``drop_connect`` the
        dropOut probability applies to weights instead (reference
        ``useDropConnect``), so input dropout is a no-op here."""
        if not train or self.dropout <= 0.0 or self.drop_connect:
            return x
        if rng is None:
            raise ValueError(f"Layer {self.name}: dropout requires an rng key at train time")
        keep = 1.0 - self.dropout
        mask = jax.random.bernoulli(rng, keep, x.shape)
        return jnp.where(mask, x / keep, 0.0)

    def maybe_drop_connect(self, W, *, train, rng):
        """DropConnect: bernoulli-mask the weight matrix at train time
        (reference ``util/Dropout.java:24-36`` applyDropConnect, with
        inverted scaling so inference needs no rescale)."""
        if not train or not self.drop_connect or self.dropout <= 0.0:
            return W
        if rng is None:
            raise ValueError(
                f"Layer {self.name}: drop_connect requires an rng key at train time")
        keep = 1.0 - self.dropout
        mask = jax.random.bernoulli(rng, keep, W.shape)
        return jnp.where(mask, W / keep, 0.0)

    # ---- regularization -------------------------------------------------
    def reg_score(self, params: Dict[str, jax.Array]) -> jax.Array:
        """L1/L2 penalty contribution (reference calcL1/calcL2 on weights only)."""
        if (self.l1 == 0.0 and self.l2 == 0.0) or not params:
            return jnp.zeros(())
        total = jnp.zeros(())
        for pname, p in params.items():
            if pname in ("b", "beta", "gamma", "mean", "var"):
                continue
            if self.l1:
                total = total + self.l1 * jnp.sum(jnp.abs(p))
            if self.l2:
                total = total + 0.5 * self.l2 * jnp.sum(p * p)
        return total

    # ---- serde ----------------------------------------------------------
    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d["type"] = type(self).__name__
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Layer":
        fields = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in fields})

    def with_name(self, name: str) -> "Layer":
        return dataclasses.replace(self, name=name)
