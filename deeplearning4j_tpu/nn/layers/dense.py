"""Feed-forward layers: Dense, Output, Activation, Dropout, Embedding.

Reference impls: ``nn/layers/feedforward/dense/DenseLayer.java``,
``nn/layers/BaseOutputLayer.java`` / ``OutputLayer.java``,
``nn/layers/feedforward/embedding/EmbeddingLayer.java``.
Param names follow the reference ("W", "b") so checkpoints/tests read naturally.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn import activations, initializers, losses
from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.nn.layers.base import Layer, register_layer


@register_layer
@dataclasses.dataclass(frozen=True)
class DenseLayer(Layer):
    n_in: Optional[int] = None
    n_out: Optional[int] = None
    _SUPPORTS_DROP_CONNECT = True  # apply() masks W via maybe_drop_connect

    def setup(self, input_type: InputType) -> "DenseLayer":
        if self.n_in is None:
            return dataclasses.replace(self, n_in=input_type.flat_size())
        return self

    def output_type(self, input_type: InputType) -> InputType:
        if input_type.kind == "rnn":
            # dense applied per-timestep (reference wraps via preprocessor;
            # here batched matmul handles [B,T,F] natively)
            return InputType.recurrent(self.n_out, input_type.timesteps)
        return InputType.feed_forward(self.n_out)

    def init(self, key, dtype=jnp.float32):
        from deeplearning4j_tpu.nn.initializers import distribution_from_dict

        w = initializers.init(
            self.weight_init, key, (self.n_in, self.n_out), dtype,
            distribution=distribution_from_dict(self.dist),
        )
        b = jnp.full((self.n_out,), self.bias_init, dtype)
        return {"W": w, "b": b}

    def apply(self, params, state, x, *, train=False, rng=None):
        x = self.maybe_dropout(x, train=train, rng=rng)
        w = self.maybe_drop_connect(params["W"], train=train, rng=rng)
        z = x @ w + params["b"]
        return activations.get(self.activation)(z), state

    def pre_output(self, params, x):
        return x @ params["W"] + params["b"]


@register_layer
@dataclasses.dataclass(frozen=True)
class OutputLayer(DenseLayer):
    """Dense + loss head (reference ``nn/layers/OutputLayer.java``).
    ``loss`` names a function in :mod:`deeplearning4j_tpu.nn.losses`."""

    loss: str = "mcxent"
    # default differs from the base "sigmoid": with the default mcxent loss
    # sigmoid degenerates (see validate); softmax is the classification
    # default users expect
    activation: str = "softmax"

    def validate(self) -> None:
        super().validate()
        losses.get(self.loss)
        if self.loss == "mcxent" and self.activation == "sigmoid":
            import warnings

            # mcxent lacks the (1-y)log(1-p) term, so with independent
            # sigmoid outputs the loss is minimised by saturating ALL units
            # to 1 — training silently degenerates (later reference versions
            # warn on this exact pairing too)
            warnings.warn(
                "OutputLayer: loss 'mcxent' with activation 'sigmoid' "
                "degenerates (all outputs ->1). Use activation='softmax' "
                "for classification or loss='xent' for multi-label.",
                stacklevel=2)

    def score(self, params, x, labels, mask=None):
        pre = self.pre_output(params, x)
        return losses.score(self.loss, labels, pre, self.activation, mask)


@register_layer
@dataclasses.dataclass(frozen=True)
class ActivationLayer(Layer):
    """Pure activation layer (reference ``nn/conf/layers/ActivationLayer``)."""

    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    def has_params(self) -> bool:
        return False

    def init(self, key, dtype=jnp.float32):
        return {}

    def apply(self, params, state, x, *, train=False, rng=None):
        return activations.get(self.activation)(x), state


@register_layer
@dataclasses.dataclass(frozen=True)
class DropoutLayer(Layer):
    """Standalone dropout (reference DropoutLayer)."""

    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    def has_params(self) -> bool:
        return False

    def init(self, key, dtype=jnp.float32):
        return {}

    def apply(self, params, state, x, *, train=False, rng=None):
        return self.maybe_dropout(x, train=train, rng=rng), state


@register_layer
@dataclasses.dataclass(frozen=True)
class EmbeddingLayer(Layer):
    """Index lookup layer (reference ``EmbeddingLayer.java``: input is a
    column of indices; forward = row gather, a TPU-native one-hot-free
    ``jnp.take``)."""

    n_in: Optional[int] = None   # vocab size
    n_out: Optional[int] = None
    activation: str = "identity"
    # reference semantics: a [B, 1] input is a COLUMN of indices and embeds
    # to [B, n_out].  Sequence models (ids [B, T]) must turn this off, or a
    # length-1 sequence is indistinguishable from a column and loses its
    # time axis (zoo.transformer_char_lm sets False).
    collapse_column: bool = True

    def setup(self, input_type: InputType) -> "EmbeddingLayer":
        if self.n_in is None:
            return dataclasses.replace(self, n_in=input_type.flat_size())
        return self

    def output_type(self, input_type: InputType) -> InputType:
        if input_type.kind == "rnn":
            return InputType.recurrent(self.n_out, input_type.timesteps)
        return InputType.feed_forward(self.n_out)

    def init(self, key, dtype=jnp.float32):
        from deeplearning4j_tpu.nn.initializers import distribution_from_dict

        w = initializers.init(
            self.weight_init, key, (self.n_in, self.n_out), dtype,
            distribution=distribution_from_dict(self.dist),
        )
        b = jnp.full((self.n_out,), self.bias_init, dtype)
        return {"W": w, "b": b}

    def apply(self, params, state, x, *, train=False, rng=None):
        idx = x.astype(jnp.int32)
        if self.collapse_column and idx.ndim >= 2 and idx.shape[-1] == 1:
            idx = idx[..., 0]
        z = jnp.take(params["W"], idx, axis=0) + params["b"]
        return activations.get(self.activation)(z), state
