"""Convolution + pooling layers.

Reference: ``nn/layers/convolution/ConvolutionLayer.java:141-172`` implements
conv as im2col -> gemm -> col2im on ND4J, with a cuDNN fast path
(``deeplearning4j-cuda/.../CudnnConvolutionHelper.java``).  TPU-native design:
one ``lax.conv_general_dilated`` in NHWC/HWIO, which XLA lowers straight onto
the MXU — the im2col materialization and the helper-plugin seam both dissolve
(XLA *is* the fast path; see deeplearning4j_tpu/ops for the Pallas escape
hatch when fusion is insufficient).

Layouts: activations NHWC, kernels HWIO.  Padding is explicit ints like the
reference (kernel/stride/padding triples), not just SAME/VALID.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn import activations, initializers
from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.nn.layers.base import Layer, register_layer


def _pair(v) -> Tuple[int, int]:
    if isinstance(v, (list, tuple)):
        return (int(v[0]), int(v[1]))
    return (int(v), int(v))


def _out_size(size, k, s, p):
    return (size + 2 * p - k) // s + 1


@register_layer
@dataclasses.dataclass(frozen=True)
class ConvolutionLayer(Layer):
    n_in: Optional[int] = None    # input channels (inferred)
    n_out: Optional[int] = None   # output channels
    kernel_size: Tuple[int, int] = (5, 5)
    stride: Tuple[int, int] = (1, 1)
    padding: Tuple[int, int] = (0, 0)
    activation: str = "identity"
    weight_init: str = "xavier"

    def __post_init__(self):
        object.__setattr__(self, "kernel_size", _pair(self.kernel_size))
        object.__setattr__(self, "stride", _pair(self.stride))
        object.__setattr__(self, "padding", _pair(self.padding))

    def setup(self, input_type: InputType) -> "ConvolutionLayer":
        if self.n_in is None:
            if input_type.kind not in ("cnn", "cnn_flat"):
                raise ValueError(f"ConvolutionLayer expects CNN input, got {input_type}")
            return dataclasses.replace(self, n_in=input_type.channels)
        return self

    def output_type(self, input_type: InputType) -> InputType:
        kh, kw = self.kernel_size
        sh, sw = self.stride
        ph, pw = self.padding
        h = _out_size(input_type.height, kh, sh, ph)
        w = _out_size(input_type.width, kw, sw, pw)
        if h <= 0 or w <= 0:
            raise ValueError(
                f"Conv output size {h}x{w} invalid for input "
                f"{input_type.height}x{input_type.width} kernel {self.kernel_size} "
                f"stride {self.stride} pad {self.padding}"
            )
        return InputType.convolutional(h, w, self.n_out)

    def init(self, key, dtype=jnp.float32):
        kh, kw = self.kernel_size
        from deeplearning4j_tpu.nn.initializers import distribution_from_dict

        w = initializers.init(
            self.weight_init, key, (kh, kw, self.n_in, self.n_out), dtype,
            distribution=distribution_from_dict(self.dist),
        )
        b = jnp.full((self.n_out,), self.bias_init, dtype)
        return {"W": w, "b": b}

    def apply(self, params, state, x, *, train=False, rng=None):
        x = self.maybe_dropout(x, train=train, rng=rng)
        x = x.astype(params["W"].dtype)
        ph, pw = self.padding
        z = lax.conv_general_dilated(
            x,
            params["W"],
            window_strides=self.stride,
            padding=((ph, ph), (pw, pw)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        z = z + params["b"]
        return activations.get(self.activation)(z), state


@register_layer
@dataclasses.dataclass(frozen=True)
class SubsamplingLayer(Layer):
    """Pooling (reference ``SubsamplingLayer.java``: MAX/AVG/SUM + cuDNN
    helper). TPU-native: ``lax.reduce_window`` — XLA fuses and the backward
    pass (scatter for max, uniform spread for avg) comes from autodiff."""

    pooling_type: str = "max"  # max | avg | sum
    kernel_size: Tuple[int, int] = (2, 2)
    stride: Tuple[int, int] = (2, 2)
    padding: Tuple[int, int] = (0, 0)
    activation: str = "identity"

    def __post_init__(self):
        object.__setattr__(self, "kernel_size", _pair(self.kernel_size))
        object.__setattr__(self, "stride", _pair(self.stride))
        object.__setattr__(self, "padding", _pair(self.padding))

    def has_params(self) -> bool:
        return False

    def init(self, key, dtype=jnp.float32):
        return {}

    def output_type(self, input_type: InputType) -> InputType:
        kh, kw = self.kernel_size
        sh, sw = self.stride
        ph, pw = self.padding
        h = _out_size(input_type.height, kh, sh, ph)
        w = _out_size(input_type.width, kw, sw, pw)
        return InputType.convolutional(h, w, input_type.channels)

    def apply(self, params, state, x, *, train=False, rng=None):
        kh, kw = self.kernel_size
        sh, sw = self.stride
        ph, pw = self.padding
        window = (1, kh, kw, 1)
        strides = (1, sh, sw, 1)
        pads = ((0, 0), (ph, ph), (pw, pw), (0, 0))
        pt = self.pooling_type.lower()
        if pt == "max":
            y = lax.reduce_window(x, -jnp.inf, lax.max, window, strides, pads)
        elif pt in ("avg", "mean"):
            s = lax.reduce_window(x, 0.0, lax.add, window, strides, pads)
            y = s / float(kh * kw)
        elif pt == "sum":
            y = lax.reduce_window(x, 0.0, lax.add, window, strides, pads)
        else:
            raise ValueError(f"Unknown pooling type {self.pooling_type}")
        return y, state


@register_layer
@dataclasses.dataclass(frozen=True)
class GlobalPoolingLayer(Layer):
    """Global spatial (or temporal) pooling: [B,H,W,C]->[B,C] or
    [B,T,F]->[B,F].  TPU-native reduction; used by ResNet-style heads."""

    pooling_type: str = "avg"  # avg | max | sum

    def has_params(self) -> bool:
        return False

    def init(self, key, dtype=jnp.float32):
        return {}

    def output_type(self, input_type: InputType) -> InputType:
        if input_type.kind == "cnn":
            return InputType.feed_forward(input_type.channels)
        return InputType.feed_forward(input_type.size)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        axes = tuple(range(1, x.ndim - 1))
        pt = self.pooling_type.lower()
        if mask is not None and x.ndim == 3:
            # masked temporal pooling: exclude padded timesteps
            m = mask[..., None]
            if pt in ("avg", "mean"):
                denom = jnp.maximum(jnp.sum(m, axis=1), 1.0)
                return jnp.sum(x * m, axis=1) / denom, state
            if pt == "max":
                neg = jnp.asarray(-jnp.inf, x.dtype)
                return jnp.max(jnp.where(m > 0, x, neg), axis=1), state
            if pt == "sum":
                return jnp.sum(x * m, axis=1), state
        if pt in ("avg", "mean"):
            return jnp.mean(x, axis=axes), state
        if pt == "max":
            return jnp.max(x, axis=axes), state
        if pt == "sum":
            return jnp.sum(x, axis=axes), state
        raise ValueError(f"Unknown pooling type {self.pooling_type}")
