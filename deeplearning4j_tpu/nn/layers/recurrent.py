"""Recurrent layers: Graves LSTM (with peepholes), bidirectional variant,
plain LSTM, and the RNN output head.

Reference: ``nn/layers/recurrent/LSTMHelpers.java:144-181`` — per-timestep
Java loop doing one gemm + gate slicing per step, peephole connections on
input/forget/output gates; ``GravesBidirectionalLSTM.java:218`` sums the two
directions.  TPU-native redesign: the input projection for ALL timesteps is
one big [B*T, n_in] x [n_in, 4H] matmul (MXU-friendly), then a ``lax.scan``
carries (h, c) with only the [B, H] x [H, 4H] recurrent matmul inside the
loop — static shapes, no per-step Python.

Sequence layout is [batch, time, features] (reference: [batch, features, time]).
Masking: per reference semantics, masked steps freeze the carried state and
zero the emitted activation (``GradientCheckTestsMasking`` contract).
Streaming inference (reference ``rnnTimeStep``/``stateMap``,
``BaseRecurrentLayer.java``) is the pure ``step`` method — the model facade
owns the state pytree.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn import activations, initializers, losses
from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.nn.layers.base import Layer, register_layer
from deeplearning4j_tpu.nn.layers.dense import OutputLayer

# Gate block order inside the fused 4H dimension: input, forget, cell(g), output.
_I, _F, _G, _O = 0, 1, 2, 3


def _lstm_init(key, n_in, n_out, weight_init, dist, peephole, dtype, prefix=""):
    from deeplearning4j_tpu.nn.initializers import distribution_from_dict

    k1, k2, k3 = jax.random.split(key, 3)
    d = distribution_from_dict(dist)
    p = {
        prefix + "W": initializers.init(weight_init, k1, (n_in, 4 * n_out), dtype,
                                        fan_in=n_in, fan_out=n_out, distribution=d),
        prefix + "RW": initializers.init(weight_init, k2, (n_out, 4 * n_out), dtype,
                                         fan_in=n_out, fan_out=n_out, distribution=d),
        # forget-gate bias init (reference forgetGateBiasInit, default 1.0)
        prefix + "b": jnp.zeros((4 * n_out,), dtype).at[n_out : 2 * n_out].set(1.0),
    }
    if peephole:
        pk = jax.random.split(k3, 3)
        for i, gate in enumerate(("pI", "pF", "pO")):
            p[prefix + gate] = initializers.init(
                weight_init, pk[i], (n_out,), dtype, fan_in=n_out, fan_out=n_out, distribution=d
            )
    return p


def _cell_step(params, act_fn, gate_act, peephole, h_prev, c_prev, xproj_t, prefix=""):
    """One LSTM cell step given the precomputed input projection for step t."""
    H = h_prev.shape[-1]
    z = xproj_t + h_prev @ params[prefix + "RW"]  # [B, 4H]
    zi, zf, zg, zo = (z[..., i * H : (i + 1) * H] for i in range(4))
    if peephole:
        zi = zi + c_prev * params[prefix + "pI"]
        zf = zf + c_prev * params[prefix + "pF"]
    i_g = gate_act(zi)
    f_g = gate_act(zf)
    g = act_fn(zg)
    c = f_g * c_prev + i_g * g
    if peephole:
        zo = zo + c * params[prefix + "pO"]
    o_g = gate_act(zo)
    h = o_g * act_fn(c)
    return h, c


def _scan_lstm(params, act_fn, gate_act, peephole, x, mask, reverse=False,
               h0=None, c0=None, prefix=""):
    """Scan over [B, T, n_in] -> [B, T, H] with state freezing on masked steps."""
    B, T, _ = x.shape
    H = params[prefix + "RW"].shape[0]
    xproj = x.reshape(B * T, -1) @ params[prefix + "W"] + params[prefix + "b"]
    xproj = xproj.reshape(B, T, 4 * H)
    h0 = jnp.zeros((B, H), x.dtype) if h0 is None else h0
    c0 = jnp.zeros((B, H), x.dtype) if c0 is None else c0

    def body(carry, inp):
        h_prev, c_prev = carry
        xp_t, m_t = inp
        h, c = _cell_step(params, act_fn, gate_act, peephole, h_prev, c_prev, xp_t, prefix)
        if m_t is not None:
            m = m_t[:, None]
            h = jnp.where(m > 0, h, h_prev)
            c = jnp.where(m > 0, c, c_prev)
            out = h * m
        else:
            out = h
        return (h, c), out

    xs = (jnp.swapaxes(xproj, 0, 1), jnp.swapaxes(mask, 0, 1) if mask is not None else None)
    if mask is None:
        xs = (xs[0], jnp.ones((T, B), x.dtype))

        def body2(carry, inp):
            return body(carry, (inp[0], None))

        (hT, cT), ys = lax.scan(body2, (h0, c0), xs, reverse=reverse)
    else:
        (hT, cT), ys = lax.scan(body, (h0, c0), xs, reverse=reverse)
    return jnp.swapaxes(ys, 0, 1), (hT, cT)


@register_layer
@dataclasses.dataclass(frozen=True)
class GravesLSTM(Layer):
    """Graves-style LSTM with peephole connections
    (reference ``nn/layers/recurrent/GravesLSTM.java:38``)."""

    n_in: Optional[int] = None
    n_out: Optional[int] = None
    activation: str = "tanh"
    gate_activation: str = "sigmoid"
    peephole: bool = True

    def setup(self, input_type: InputType) -> "GravesLSTM":
        if self.n_in is None:
            return dataclasses.replace(self, n_in=input_type.size)
        return self

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.recurrent(self.n_out, input_type.timesteps)

    def init(self, key, dtype=jnp.float32):
        return _lstm_init(key, self.n_in, self.n_out, self.weight_init, self.dist,
                          self.peephole, dtype)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        y, _st, _carry = self.apply_with_carry(params, state, x, None,
                                               train=train, rng=rng, mask=mask)
        return y, _st

    def apply_with_carry(self, params, state, x, carry, *, train=False, rng=None, mask=None):
        """Sequence forward exposing the final (h, c) carry — the functional
        form of the reference's TBPTT state plumbing
        (``MultiLayerNetwork.java:1176`` rnnActivateUsingStoredState)."""
        x = self.maybe_dropout(x, train=train, rng=rng)
        h0, c0 = carry if carry is not None else (None, None)
        ys, (hT, cT) = _scan_lstm(
            params, activations.get(self.activation),
            activations.get(self.gate_activation), self.peephole, x, mask,
            h0=h0, c0=c0,
        )
        return ys, state, (hT, cT)

    # -- streaming inference (reference rnnTimeStep / stateMap) ------------
    def initial_carry(self, batch: int, dtype=jnp.float32):
        return (jnp.zeros((batch, self.n_out), dtype), jnp.zeros((batch, self.n_out), dtype))

    def step(self, params, carry, x_t):
        """One timestep: x_t [B, n_in] -> (y [B, H], new_carry)."""
        h_prev, c_prev = carry
        xproj = x_t @ params["W"] + params["b"]
        h, c = _cell_step(
            params, activations.get(self.activation),
            activations.get(self.gate_activation), self.peephole, h_prev, c_prev, xproj,
        )
        return h, (h, c)


@register_layer
@dataclasses.dataclass(frozen=True)
class LSTM(GravesLSTM):
    """Standard LSTM without peepholes (XLA fuses gates into two matmuls per
    step; the fast default for new models)."""

    peephole: bool = False


@register_layer
@dataclasses.dataclass(frozen=True)
class GravesBidirectionalLSTM(Layer):
    """Bidirectional Graves LSTM; directions are summed
    (reference ``GravesBidirectionalLSTM.java:218`` ``fwdOutput.addi(backOutput)``)."""

    n_in: Optional[int] = None
    n_out: Optional[int] = None
    activation: str = "tanh"
    gate_activation: str = "sigmoid"
    peephole: bool = True

    def setup(self, input_type: InputType) -> "GravesBidirectionalLSTM":
        if self.n_in is None:
            return dataclasses.replace(self, n_in=input_type.size)
        return self

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.recurrent(self.n_out, input_type.timesteps)

    def init(self, key, dtype=jnp.float32):
        kf, kb = jax.random.split(key)
        p = _lstm_init(kf, self.n_in, self.n_out, self.weight_init, self.dist,
                       self.peephole, dtype, prefix="f_")
        p.update(_lstm_init(kb, self.n_in, self.n_out, self.weight_init, self.dist,
                            self.peephole, dtype, prefix="b_"))
        return p

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = self.maybe_dropout(x, train=train, rng=rng)
        act = activations.get(self.activation)
        gact = activations.get(self.gate_activation)
        fwd, _ = _scan_lstm(params, act, gact, self.peephole, x, mask, prefix="f_")
        bwd, _ = _scan_lstm(params, act, gact, self.peephole, x, mask, reverse=True, prefix="b_")
        return fwd + bwd, state


@register_layer
@dataclasses.dataclass(frozen=True)
class RnnOutputLayer(OutputLayer):
    """Per-timestep dense + loss head (reference ``RnnOutputLayer.java``).
    Input [B, T, n_in] -> [B, T, n_out]; loss masks over [B, T]."""

    def setup(self, input_type: InputType) -> "RnnOutputLayer":
        if self.n_in is None:
            return dataclasses.replace(self, n_in=input_type.size)
        return self

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.recurrent(self.n_out, input_type.timesteps)
