"""Normalization layers: BatchNorm and LRN.

Reference: ``nn/layers/normalization/BatchNormalization.java`` (rank-2 dense
and rank-4 conv paths, running mean/var with decay, gamma/beta optionally
locked), ``LocalResponseNormalization.java`` (k, n, alpha, beta across-channel
LRN), both with cuDNN helper hooks.  TPU-native: pure jnp reductions that XLA
fuses; running stats live in the layer *state* pytree (the functional answer
to the reference's mutable fields), updated only when ``train=True``.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn import activations
from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.nn.layers.base import Layer, register_layer


@register_layer
@dataclasses.dataclass(frozen=True)
class BatchNormalization(Layer):
    n_out: Optional[int] = None   # feature/channel count (inferred)
    decay: float = 0.9            # running-average decay (reference default)
    eps: float = 1e-5
    lock_gamma_beta: bool = False # reference lockGammaBeta: fixed gamma/beta
    gamma: float = 1.0
    beta: float = 0.0
    activation: str = "identity"

    def setup(self, input_type: InputType) -> "BatchNormalization":
        if self.n_out is None:
            n = input_type.channels if input_type.kind == "cnn" else input_type.flat_size()
            return dataclasses.replace(self, n_out=n)
        return self

    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    def init(self, key, dtype=jnp.float32):
        if self.lock_gamma_beta:
            return {}
        return {
            "gamma": jnp.full((self.n_out,), self.gamma, dtype),
            "beta": jnp.full((self.n_out,), self.beta, dtype),
        }

    def init_state(self):
        return {
            "mean": jnp.zeros((self.n_out,), jnp.float32),
            "var": jnp.ones((self.n_out,), jnp.float32),
        }

    def apply(self, params, state, x, *, train=False, rng=None):
        # reduce over all axes except the trailing feature/channel axis —
        # covers both the rank-2 dense and rank-4 NHWC conv paths uniformly
        # (reference needed two separate code paths, BatchNormalization.java:116)
        axes = tuple(range(x.ndim - 1))
        if train:
            # helper fast path (≙ cudnnBatchNormalizationForwardTraining):
            # fused mean/var/normalize in one VMEM pass, fused backward VJP
            from deeplearning4j_tpu import helpers as _h

            helper = _h.get_helper("batch_norm")
            if (helper is not None and hasattr(helper, "apply_training")
                    and helper.supports(x) and x.ndim == 2):
                gamma = (jnp.full((self.n_out,), self.gamma, x.dtype)
                         if self.lock_gamma_beta else params["gamma"])
                beta = (jnp.full((self.n_out,), self.beta, x.dtype)
                        if self.lock_gamma_beta else params["beta"])
                y, mean, var = helper.apply_training(x, gamma, beta, self.eps)
                new_state = {
                    "mean": self.decay * state["mean"]
                            + (1 - self.decay) * jax.lax.stop_gradient(mean),
                    "var": self.decay * state["var"]
                           + (1 - self.decay) * jax.lax.stop_gradient(var),
                }
                return activations.get(self.activation)(y), new_state
            mean = jnp.mean(x, axis=axes)
            var = jnp.var(x, axis=axes)
            new_state = {
                "mean": self.decay * state["mean"] + (1 - self.decay) * mean,
                "var": self.decay * state["var"] + (1 - self.decay) * var,
            }
        else:
            mean, var = state["mean"], state["var"]
            new_state = state
            # helper fast path (≙ cuDNN BN helper hook, BatchNormalization
            # .java:116-121): fused Pallas inference pass when available
            from deeplearning4j_tpu import helpers as _h

            helper = _h.get_helper("batch_norm")
            if helper is not None and helper.supports(x):
                gamma = (jnp.full((self.n_out,), self.gamma, x.dtype)
                         if self.lock_gamma_beta else params["gamma"])
                beta = (jnp.full((self.n_out,), self.beta, x.dtype)
                        if self.lock_gamma_beta else params["beta"])
                y = helper.apply_inference(x, mean, var, gamma, beta, self.eps)
                return activations.get(self.activation)(y), new_state
        xhat = (x - mean) * lax.rsqrt(var + self.eps)
        if self.lock_gamma_beta:
            y = self.gamma * xhat + self.beta
        else:
            y = params["gamma"] * xhat + params["beta"]
        return activations.get(self.activation)(y), new_state


@register_layer
@dataclasses.dataclass(frozen=True)
class LocalResponseNormalization(Layer):
    """Across-channel LRN: y = x / (k + alpha*sum_{j in window} x_j^2)^beta.
    Reference defaults k=2, n=5, alpha=1e-4, beta=0.75
    (``nn/conf/layers/LocalResponseNormalization``)."""

    k: float = 2.0
    n: int = 5
    alpha: float = 1e-4
    beta: float = 0.75

    def has_params(self) -> bool:
        return False

    def init(self, key, dtype=jnp.float32):
        return {}

    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    def apply(self, params, state, x, *, train=False, rng=None):
        # helper fast path (≙ CudnnLocalResponseNormalizationHelper hook)
        from deeplearning4j_tpu import helpers as _h

        helper = _h.get_helper("lrn")
        if helper is not None and helper.supports(x):
            return helper.apply(x, self.k, self.n, self.alpha, self.beta), state
        # NHWC: window-sum x^2 along the channel axis via reduce_window
        half = self.n // 2
        sq = x * x
        window_sum = lax.reduce_window(
            sq, 0.0, lax.add,
            window_dimensions=(1, 1, 1, self.n),
            window_strides=(1, 1, 1, 1),
            padding=((0, 0), (0, 0), (0, 0), (half, half)),
        )
        denom = jnp.power(self.k + self.alpha * window_sum, self.beta)
        return x / denom, state


@register_layer
@dataclasses.dataclass(frozen=True)
class LayerNorm(Layer):
    """Per-example feature normalization (no reference analog — the
    reference is pre-transformer; needed by the attention stack).
    Normalizes over the trailing feature axis, so it is exactly
    sequence-shard-safe: under sequence parallelism every timestep
    normalizes locally with no collective."""

    n_in: Optional[int] = None
    eps: float = 1e-5
    activation: str = "identity"

    def setup(self, input_type: InputType) -> "LayerNorm":
        if self.n_in is None:
            return dataclasses.replace(self, n_in=input_type.size)
        return self

    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    def init(self, key, dtype=jnp.float32):
        return {
            "gamma": jnp.ones((self.n_in,), dtype),
            "beta": jnp.zeros((self.n_in,), dtype),
        }

    def apply(self, params, state, x, *, train=False, rng=None):
        mu = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.var(x, axis=-1, keepdims=True)
        y = (x - mu) * lax.rsqrt(var + self.eps)
        y = params["gamma"] * y + params["beta"]
        return activations.get(self.activation)(y), state
