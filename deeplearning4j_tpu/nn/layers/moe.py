"""Mixture-of-Experts layer — expert parallelism (EP) building block.

Beyond-reference extension (the reference predates MoE; SURVEY.md §2 lists
EP as absent).  TPU-first design: top-1 "switch" routing with a fixed
per-expert capacity so every shape is static — dispatch and combine are
one-hot einsums that lower to MXU matmuls, and the expert dimension of
every parameter is sharded over the mesh's model axis by the tensor/expert
parallel training master (``parallel/model_parallel.py``), putting each
expert's FFN on its own chips with all-to-all dispatch inserted by GSPMD.

Tokens over a full expert's capacity are dropped (contribute the residual
path only) — standard Switch-Transformer semantics that keeps the program
shape-static under jit.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn import activations, initializers
from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.nn.layers.base import Layer, register_layer


@register_layer
@dataclasses.dataclass(frozen=True)
class MoELayer(Layer):
    """Switch-routed expert FFN: x -> router -> expert MLP -> combine.

    n_in/n_out: model width (input preserved: experts are hidden FFNs with a
    residual add, transformer-style).  hidden: per-expert FFN width.
    """

    n_in: Optional[int] = None
    n_out: Optional[int] = None
    num_experts: int = 4
    hidden: int = 0                   # default 4*n_in
    capacity_factor: float = 1.25
    activation: str = "relu"
    residual: bool = True

    def setup(self, input_type: InputType) -> "MoELayer":
        n_in = self.n_in if self.n_in is not None else input_type.flat_size()
        n_out = self.n_out if self.n_out is not None else n_in
        return dataclasses.replace(self, n_in=n_in, n_out=n_out)

    def output_type(self, input_type: InputType) -> InputType:
        if input_type.kind == "rnn":
            return InputType.recurrent(self.n_out, input_type.timesteps)
        return InputType.feed_forward(self.n_out)

    def validate(self) -> None:
        super().validate()
        if self.residual and self.n_in != self.n_out:
            raise ValueError("MoE residual path needs n_in == n_out")

    def init(self, key, dtype=jnp.float32) -> Dict[str, jax.Array]:
        h = self.hidden or 4 * self.n_in
        k1, k2, k3, k4 = jax.random.split(key, 4)
        E = self.num_experts

        def w(k, shape, fan_in, fan_out):
            return initializers.init(self.weight_init, k, shape, dtype,
                                     fan_in=fan_in, fan_out=fan_out)

        return {
            "W_router": w(k1, (self.n_in, E), self.n_in, E),
            "W_up": w(k2, (E, self.n_in, h), self.n_in, h),
            "b_up": jnp.zeros((E, h), dtype),
            "W_down": w(k3, (E, h, self.n_out), h, self.n_out),
            "b_down": jnp.zeros((E, self.n_out), dtype),
        }

    def _capacity(self, n_tokens: int) -> int:
        return max(1, int(self.capacity_factor * n_tokens
                          / self.num_experts))

    def apply(self, params, state, x, *, train=False, rng=None):
        x = self.maybe_dropout(x, train=train, rng=rng)
        orig_shape = x.shape
        tokens = x.reshape(-1, orig_shape[-1])           # [T, d]
        T = tokens.shape[0]
        E = self.num_experts
        C = self._capacity(T)

        logits = tokens @ params["W_router"]             # [T, E]
        gates = jax.nn.softmax(logits, axis=-1)
        expert = jnp.argmax(gates, axis=-1)              # [T]
        gate = jnp.take_along_axis(gates, expert[:, None], 1)[:, 0]

        # position of each token within its expert's capacity buffer
        onehot = jax.nn.one_hot(expert, E, dtype=tokens.dtype)   # [T, E]
        pos = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot        # [T, E]
        in_cap = (pos < C) & (onehot > 0)                        # [T, E]
        # dispatch tensor [T, E, C]: token t -> slot (e, c)
        slot = jax.nn.one_hot(pos.astype(jnp.int32), C,
                              dtype=tokens.dtype) * in_cap[..., None]
        expert_in = jnp.einsum("tec,td->ecd", slot, tokens)      # [E, C, d]

        act = activations.get(self.activation)
        hdn = act(jnp.einsum("ecd,edh->ech", expert_in, params["W_up"])
                  + params["b_up"][:, None, :])
        out = (jnp.einsum("ech,eho->eco", hdn, params["W_down"])
               + params["b_down"][:, None, :])                   # [E, C, o]

        combined = jnp.einsum("tec,eco->to", slot, out)          # [T, o]
        combined = combined * gate[:, None]
        if self.residual:
            combined = combined + tokens
        return combined.reshape(orig_shape[:-1] + (self.n_out,)), state
