"""Multi-head self-attention layers (TPU-first long-context extension).

The reference is pre-transformer — its only long-sequence tools are
truncated BPTT + masking (``nn/multilayer/MultiLayerNetwork.java:1176``,
``:711``).  This framework makes long-context first-class: a fused-friendly
local attention layer here, and ring / Ulysses sequence-parallel execution in
:mod:`deeplearning4j_tpu.parallel.sequence_parallel` for sequences that do
not fit one chip.

Design notes (TPU):
  - attention is computed head-batched as one ``jnp.einsum`` pair so XLA maps
    it onto the MXU; no per-head Python loops.
  - the layer is time-layout ``[B, T, F]`` like the rest of the recurrent
    stack; masks broadcast ``[B, T]``.
  - when ``seq_axis`` is set the layer computes ring attention over that
    mesh axis (caller runs the step under ``shard_map`` — see
    ``SequenceParallelTrainingMaster``); sequence shards never gather.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn import activations, initializers
from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.nn.layers.base import Layer, register_layer


def split_heads(x: jax.Array, n_heads: int) -> jax.Array:
    """[B, T, H*D] -> [B, T, H, D]"""
    b, t, f = x.shape
    return x.reshape(b, t, n_heads, f // n_heads)


def merge_heads(x: jax.Array) -> jax.Array:
    """[B, T, H, D] -> [B, T, H*D]"""
    b, t, h, d = x.shape
    return x.reshape(b, t, h * d)


def check_window(causal: bool, window: Optional[int]) -> None:
    """Single source of truth for the sliding-window contract: every entry
    point (flash, einsum, ring, layer config) fails loudly the same way."""
    if window is not None and (not causal or window < 1):
        raise ValueError(
            f"window={window} requires causal=True and window >= 1")


def rope(x: jax.Array, positions: jax.Array,
         theta: float = 10000.0) -> jax.Array:
    """Rotary position embedding on ``[B, T, H, D]`` (RoFormer; public
    standard).  ``positions`` is the [T] vector of GLOBAL positions —
    or, for the paged continuous-batching decode path where every batch
    row sits at a different stream position, a per-row [B, T] matrix —
    which is what makes the same function serve the full-sequence path,
    the streaming KV-cache path (q at ``pos + arange``, k rotated at
    write time), paged decode (per-slot positions), and ring attention
    (shard offsets).  Odd tail dims (D not a multiple of 2) pass through
    unrotated."""
    d = x.shape[-1]
    half = d // 2
    acc = jnp.promote_types(x.dtype, jnp.float32)
    freqs = jnp.power(jnp.asarray(theta, acc),
                      -jnp.arange(0, half, dtype=acc) / max(half, 1))
    ang = positions.astype(acc)[..., :, None] * freqs  # [(B,) T, half]
    if positions.ndim == 1:
        cos = jnp.cos(ang)[None, :, None, :]
        sin = jnp.sin(ang)[None, :, None, :]
    else:
        cos = jnp.cos(ang)[:, :, None, :]
        sin = jnp.sin(ang)[:, :, None, :]
    x1 = x[..., :half].astype(acc)
    x2 = x[..., half:2 * half].astype(acc)
    out = jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin, x[..., 2 * half:].astype(acc)],
        axis=-1)
    return out.astype(x.dtype)


def dot_product_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    window: Optional[int] = None,
    mask: Optional[jax.Array] = None,
    q_offset: int | jax.Array = 0,
    k_offset: int | jax.Array = 0,
    q_positions: Optional[jax.Array] = None,
    k_positions: Optional[jax.Array] = None,
) -> jax.Array:
    """Scaled dot-product attention on ``[B, T, H, D]`` tensors.

    ``q_offset``/``k_offset`` give the global time positions of the local
    q/k blocks — this is what lets the same function serve as the per-block
    kernel of ring attention (blockwise causal masking by global position).
    Accumulates in float32 regardless of input dtype (MXU-friendly inputs,
    stable softmax).

    Grouped-query attention: when q carries MORE heads than k/v
    (H = G * H_kv) the contraction shares each KV head across its G query
    heads WITHOUT materializing an expanded K/V — the bandwidth this mode
    exists to save.
    """
    check_window(causal, window)
    d = q.shape[-1]
    hq, hkv = q.shape[2], k.shape[2]
    acc = jnp.promote_types(q.dtype, jnp.float32)   # f32 accumulate, f64 for gradchecks
    grouped = hq != hkv
    if grouped:
        if hq % hkv:
            raise ValueError(f"q heads {hq} not a multiple of kv heads {hkv}")
        qg = q.reshape(q.shape[0], q.shape[1], hkv, hq // hkv, d)
        scores = jnp.einsum("bqhgd,bkhd->bhgqk", qg, k).astype(acc)
    else:
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(acc)
    scores = scores / jnp.sqrt(jnp.asarray(d, acc))
    neg = jnp.asarray(-1e30, acc)
    head_dims = (None,) * (scores.ndim - 3)   # axes between batch and [q,k]
    if causal:
        # explicit position vectors override the contiguous offset+arange
        # convention (rolling KV caches store keys out of order)
        qpos = (q_positions if q_positions is not None
                else q_offset + jnp.arange(q.shape[1]))
        kpos = (k_positions if k_positions is not None
                else k_offset + jnp.arange(k.shape[1]))
        cm = qpos[:, None] >= kpos[None, :]
        if window is not None:
            # sliding window: keep kpos in [qpos - window + 1, qpos]
            cm &= kpos[None, :] > qpos[:, None] - window
        scores = jnp.where(cm[(None,) + head_dims], scores, neg)
    if mask is not None:
        idx = (slice(None),) + head_dims + (None, slice(None))
        scores = jnp.where(mask[idx].astype(bool), scores, neg)
    w = jax.nn.softmax(scores, axis=-1)
    if grouped:
        o = jnp.einsum("bhgqk,bkhd->bqhgd", w.astype(v.dtype), v)
        return o.reshape(q.shape[0], q.shape[1], hq, d)
    return jnp.einsum("bhqk,bkhd->bqhd", w.astype(v.dtype), v)


def gather_pages(pages: jax.Array, block: jax.Array,
                 page_size: int) -> jax.Array:
    """Materialize one batch's logical KV view from a paged pool.

    ``pages`` [P * page_size, Hkv, D] (the flattened pool), ``block``
    [B, MAXP] int32 per-row page ids: returns [B, MAXP * page_size, Hkv,
    D] where flat position ``i`` of row ``b`` is global stream position
    ``i`` of that row's sequence.  This is the paged-gather seam — the
    fused decode-attention helper (roadmap item 1,
    ``helpers/paged_attention.py``) replaces exactly this gather + the
    softmax that follows, and is the DEFAULT decode path; this function
    + ``paged_attention`` remain the flag-selectable bit-compatible
    oracle (``DL4J_TPU_PAGED_GATHER=1`` or
    ``set_paged_attention_mode("gather")``)."""
    b, maxp = block.shape
    slots = block[:, :, None] * page_size + jnp.arange(page_size)[None, None]
    return pages[slots.reshape(b, maxp * page_size)]


def paged_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    q_positions: jax.Array) -> jax.Array:
    """Causal attention of ``q`` [B, T, H, D] over a gathered paged view
    ``k``/``v`` [B, L, Hkv, D] whose flat index IS the global position
    (see ``gather_pages``).  ``q_positions`` [B, T] are per-row global
    query positions — every batch row sits at a different point of its
    own stream, which is the whole point of continuous batching, so the
    causal mask is per-row (``dot_product_attention`` masks by a single
    shared position vector and cannot express this).  Pages past a row's
    current position hold garbage (unwritten, or bucket-padding scratch);
    ``kpos > qpos`` masks every one of them.  GQA contracts the
    UNEXPANDED kv heads, same as the other paths."""
    d = q.shape[-1]
    hq, hkv = q.shape[2], k.shape[2]
    acc = jnp.promote_types(q.dtype, jnp.float32)
    if hq % hkv:
        raise ValueError(f"q heads {hq} not a multiple of kv heads {hkv}")
    grouped = hq != hkv
    kpos = jnp.arange(k.shape[1])
    cm = q_positions[:, :, None] >= kpos[None, None, :]   # [B, T, L]
    neg = jnp.asarray(-1e30, acc)
    if grouped:
        qg = q.reshape(q.shape[0], q.shape[1], hkv, hq // hkv, d)
        scores = jnp.einsum("bqhgd,bkhd->bhgqk", qg, k).astype(acc)
        scores = scores / jnp.sqrt(jnp.asarray(d, acc))
        scores = jnp.where(cm[:, None, None], scores, neg)
        w = jax.nn.softmax(scores, axis=-1)
        o = jnp.einsum("bhgqk,bkhd->bqhgd", w.astype(v.dtype), v)
        return o.reshape(q.shape[0], q.shape[1], hq, d)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(acc)
    scores = scores / jnp.sqrt(jnp.asarray(d, acc))
    scores = jnp.where(cm[:, None], scores, neg)
    w = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", w.astype(v.dtype), v)


@register_layer
@dataclasses.dataclass(frozen=True)
class SelfAttentionLayer(Layer):
    """Multi-head self-attention over ``[B, T, F]``.

    Params follow the framework's reference-style short names:
    ``Wq/Wk/Wv/Wo`` + ``bq/bk/bv/bo``.  ``causal=True`` gives decoder
    (language-model) masking.  ``seq_axis`` switches the inner product to
    ring attention over that mesh axis (requires shard_map execution).
    """

    n_in: Optional[int] = None
    n_out: Optional[int] = None
    n_heads: int = 4
    causal: bool = False
    activation: str = "identity"
    seq_axis: Optional[str] = None
    # fused Pallas flash-attention path via the helper seam
    # (helpers.get_helper("attention")) — used automatically on TPU when the
    # shape qualifies (T tiles into blocks) and no padding mask is present;
    # set False (or DL4J_TPU_DISABLE_HELPERS=1) to force the einsum path
    flash: bool = True
    # streaming-inference KV cache capacity (rnn_time_step); static so the
    # decode step compiles once
    max_cache: int = 1024
    # rotary position embedding (RoPE) on q/k before attention; parameter-
    # free, composes with the flash kernel (rotation happens outside it),
    # the KV cache (keys rotated at write by global position), and the
    # ring/Ulysses sequence-parallel paths (global shard offsets)
    rope: bool = False
    rope_theta: float = 10000.0
    # grouped-query attention: project K/V to this many heads (must divide
    # n_heads) and share each KV head across n_heads/n_kv_heads query
    # heads.  Shrinks the KV projections AND the streaming cache by the
    # same factor — the decode-bandwidth win; None = standard MHA
    n_kv_heads: Optional[int] = None
    # sliding-window (banded causal) attention: each query attends only the
    # last `window` positions.  The flash kernel skips out-of-band blocks'
    # compute AND HBM fetches; the einsum/ring paths apply the band as
    # masking (full score matrices); streaming decode uses a window-length
    # ROLLING cache (position-tracked ring buffer) — O(window) memory for
    # unbounded decode
    window: Optional[int] = None

    def setup(self, input_type: InputType) -> "SelfAttentionLayer":
        upd = {}
        if self.n_in is None:
            upd["n_in"] = input_type.size
        if self.n_out is None:
            upd["n_out"] = upd.get("n_in", self.n_in)
        return dataclasses.replace(self, **upd) if upd else self

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.recurrent(self.n_out, input_type.timesteps)

    @property
    def _kv_heads(self) -> int:
        return self.n_heads if self.n_kv_heads is None else self.n_kv_heads

    def _expand_kv(self, x: jax.Array) -> jax.Array:
        """[B, T, Hkv, D] -> [B, T, H, D]: share each KV head across its
        query-head group (GQA)."""
        groups = self.n_heads // self._kv_heads
        return x if groups == 1 else jnp.repeat(x, groups, axis=2)

    def init(self, key, dtype=jnp.float32):
        if self.n_out % self.n_heads:
            raise ValueError(
                f"n_out={self.n_out} not divisible by n_heads={self.n_heads}")
        if self._kv_heads < 1 or self.n_heads % self._kv_heads:
            raise ValueError(
                f"n_kv_heads={self.n_kv_heads} must be a positive divisor "
                f"of n_heads={self.n_heads}")
        check_window(self.causal, self.window)
        kv_out = self._kv_heads * (self.n_out // self.n_heads)
        ks = jax.random.split(key, 4)
        p: Dict[str, jax.Array] = {}
        for name, k, (fi, fo) in (
            ("Wq", ks[0], (self.n_in, self.n_out)),
            ("Wk", ks[1], (self.n_in, kv_out)),
            ("Wv", ks[2], (self.n_in, kv_out)),
            ("Wo", ks[3], (self.n_out, self.n_out)),
        ):
            p[name] = initializers.init(self.weight_init, k, (fi, fo), dtype)
            p["b" + name[1].lower()] = jnp.zeros((fo,), dtype)
        return p

    def init_cache(self, batch: int, dtype=jnp.float32) -> Dict[str, jax.Array]:
        """KV cache for streaming inference (``rnn_time_step`` on
        transformer stacks — the attention analog of the reference's RNN
        ``stateMap``, ``BaseRecurrentLayer.java``).

        Linear mode (no ``window``): ``max_cache`` slots, ``pos`` counts
        filled timesteps, overflow is a hard error.  Rolling mode
        (``window`` set): ``window`` slots written modulo, each slot's
        GLOBAL position tracked in ``kpos`` — unbounded decode length in
        O(window) memory (out-of-band keys are overwritten exactly when
        they leave the band)."""
        d_head = self.n_out // self.n_heads
        # GQA caches store the UNEXPANDED kv heads — the decode-memory win
        length = self.window if self.window is not None else self.max_cache
        shape = (batch, length, self._kv_heads, d_head)
        cache = {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype),
                 "pos": jnp.zeros((), jnp.int32)}
        if self.window is not None:
            # sentinel far below any reachable qpos - window bound
            cache["kpos"] = jnp.full((length,), jnp.iinfo(jnp.int32).min // 2,
                                     jnp.int32)
        return cache

    def init_paged_cache(self, num_pages: int, page_size: int,
                         dtype=jnp.float32) -> Dict[str, jax.Array]:
        """KV pool for PAGED streaming inference (the continuous-batching
        generation engine, ``deeplearning4j_tpu/generation/``): instead of
        one contiguous [B, max_cache] cache per stream, K/V live in a
        shared pool of ``num_pages`` fixed-size pages; each running
        request addresses its pages through an int32 block table the
        engine passes per dispatch (``carry["block"]``/``carry["pos"]``
        alongside these pools).  Pool shapes are the ONLY shapes XLA ever
        sees, so slot count and pool size close the decode shape set.
        Like the linear cache, GQA pools store the UNEXPANDED kv heads."""
        if self.window is not None:
            raise ValueError(
                "paged KV caching does not support sliding-window "
                f"attention (window={self.window}): pages are addressed "
                "by absolute position; use the rolling cache for "
                "windowed streaming")
        if not self.causal or self.seq_axis is not None:
            raise ValueError(
                "paged KV caching requires causal=True attention without "
                f"seq_axis (got causal={self.causal}, "
                f"seq_axis={self.seq_axis})")
        d_head = self.n_out // self.n_heads
        shape = (num_pages, page_size, self._kv_heads, d_head)
        return {"pk": jnp.zeros(shape, dtype), "pv": jnp.zeros(shape, dtype)}

    def _apply_paged(self, params, state, q, k, v, carry):
        """The paged-gather decode path (sibling of the rolling/linear
        branches below): write this chunk's K/V into the pool at the
        rows' global positions through the block table, gather each
        row's logical view back, attend causally by per-row position.
        Write-before-gather is correct here (pages never overwrite
        in-band keys, unlike the rolling ring) and makes the chunk's own
        keys visible to its own later queries."""
        block, pos = carry["block"], carry["pos"]      # [B, MAXP], [B]
        ps = carry["pk"].shape[1]
        t_new = q.shape[1]
        new_pos = pos[:, None] + jnp.arange(t_new, dtype=pos.dtype)
        if self.rope:
            # rotate by each ROW's global positions (rows sit at
            # different points of their own streams)
            q = rope(q, new_pos, self.rope_theta)
            k = rope(k, new_pos, self.rope_theta)
        page = jnp.take_along_axis(block, new_pos // ps, axis=1)
        flat = (page * ps + new_pos % ps).reshape(-1)
        hkv, dh = k.shape[2], k.shape[3]
        pkf = carry["pk"].reshape(-1, hkv, dh)
        pvf = carry["pv"].reshape(-1, hkv, dh)
        pkf = pkf.at[flat].set(k.reshape(-1, hkv, dh).astype(pkf.dtype))
        pvf = pvf.at[flat].set(v.reshape(-1, hkv, dh).astype(pvf.dtype))
        from deeplearning4j_tpu.helpers import get_helper

        helper = get_helper("paged_attention")
        if helper is not None and helper.supports(q, ps):
            # fused paged decode attention (roadmap item 1): attends
            # straight off the pool + block table, never materializing
            # the gathered [B, MAXP*page_size, Hkv, D] view
            o = helper.attend(q, pkf, pvf, block, new_pos, page_size=ps)
        else:
            # legacy gather+softmax oracle (DL4J_TPU_PAGED_GATHER=1)
            gk = gather_pages(pkf, block, ps).astype(q.dtype)
            gv = gather_pages(pvf, block, ps).astype(q.dtype)
            o = paged_attention(q, gk, gv, new_pos)
        new_carry = {"pk": pkf.reshape(carry["pk"].shape),
                     "pv": pvf.reshape(carry["pv"].shape),
                     "block": block, "pos": pos + t_new}
        y = merge_heads(o) @ params["Wo"] + params["bo"]
        return activations.get(self.activation)(y), state, new_carry

    @staticmethod
    def cache_overflow(carry, t_new: int, pos: Optional[int] = None) -> bool:
        """Would appending ``t_new`` steps exceed the cache?  Checked
        host-side before dispatch: ``dynamic_update_slice`` CLAMPS an
        out-of-range start index, which would silently relocate keys.
        Rolling (windowed) caches never overflow.

        ``pos`` is the host-side stream position the facades track; when
        omitted, falls back to syncing the device scalar (fine for one-off
        checks, a per-token round-trip in a decode loop)."""
        if "kpos" in carry:
            return False
        if pos is None:
            pos = int(carry["pos"])
        return pos + t_new > carry["k"].shape[1]

    def apply_with_carry(self, params, state, x, carry, *, train=False,
                         rng=None, mask=None):
        """carry=None -> exact full-sequence apply (training and batch
        inference paths are untouched).  With a cache carry: append this
        call's K/V and attend the new queries over the cached prefix —
        O(T_new · pos) per call on linear caches, O(T_new · window) on
        rolling (windowed) ones."""
        if carry is None:
            y, st = self.apply(params, state, x, train=train, rng=rng,
                               mask=mask)
            return y, st, None
        if not self.causal or self.seq_axis is not None or mask is not None:
            raise ValueError(
                "KV-cache streaming requires causal=True attention without "
                "seq_axis or padding masks (a non-causal layer would attend "
                "into the unfilled cache tail); got "
                f"causal={self.causal}, seq_axis={self.seq_axis}, "
                f"mask={'set' if mask is not None else None}")
        x = self.maybe_dropout(x, train=train, rng=rng)
        q = split_heads(x @ params["Wq"] + params["bq"], self.n_heads)
        k = split_heads(x @ params["Wk"] + params["bk"], self._kv_heads)
        v = split_heads(x @ params["Wv"] + params["bv"], self._kv_heads)
        if "pk" in carry:
            # paged mode (continuous batching): per-ROW positions and a
            # block-table-addressed pool; see _apply_paged
            return self._apply_paged(params, state, q, k, v, carry)
        t_new = q.shape[1]
        pos = carry["pos"]
        new_pos = pos + jnp.arange(t_new, dtype=pos.dtype)
        if self.rope:
            # rotate by GLOBAL position; cached keys are stored rotated
            q = rope(q, new_pos, self.rope_theta)
            k = rope(k, new_pos, self.rope_theta)
        if "kpos" in carry:
            # rolling mode: attend over [old ring buffer || this chunk]
            # (writing first would clobber keys still in-band for the
            # chunk's earlier rows), then write the chunk's tail modulo
            # the window-sized buffer for the next call
            L = carry["k"].shape[1]
            k_all = jnp.concatenate(
                [carry["k"].astype(q.dtype), k.astype(q.dtype)], axis=1)
            v_all = jnp.concatenate(
                [carry["v"].astype(q.dtype), v.astype(q.dtype)], axis=1)
            kpos_all = jnp.concatenate([carry["kpos"], new_pos])
            o = dot_product_attention(
                q, k_all, v_all, causal=True, window=self.window,
                q_positions=new_pos, k_positions=kpos_all)
            if t_new > L:   # only the last L positions can stay cached
                k, v, wpos = k[:, -L:], v[:, -L:], new_pos[-L:]
            else:
                wpos = new_pos
            slots = wpos % L   # consecutive positions -> distinct slots
            kc = carry["k"].at[:, slots].set(k.astype(carry["k"].dtype))
            vc = carry["v"].at[:, slots].set(v.astype(carry["v"].dtype))
            kposc = carry["kpos"].at[slots].set(wpos)
            new_carry = {"k": kc, "v": vc, "pos": pos + t_new, "kpos": kposc}
        else:
            zero = jnp.zeros((), pos.dtype)
            kc = jax.lax.dynamic_update_slice(
                carry["k"], k.astype(carry["k"].dtype),
                (zero, pos, zero, zero))
            vc = jax.lax.dynamic_update_slice(
                carry["v"], v.astype(carry["v"].dtype),
                (zero, pos, zero, zero))
            # causal masking by global position also hides the unfilled
            # tail (kpos > qpos).  Overflow past max_cache is a hard error,
            # enforced host-side by rnn_time_step (dynamic_update_slice
            # would clamp the write and silently relocate keys); see
            # cache_overflow().  Grouped contraction over the UNEXPANDED
            # cache — the decode-bandwidth win GQA exists for.
            o = dot_product_attention(
                q, kc.astype(q.dtype), vc.astype(q.dtype),
                causal=True, window=self.window, q_offset=pos, k_offset=0)
            new_carry = {"k": kc, "v": vc, "pos": pos + t_new}
        y = merge_heads(o) @ params["Wo"] + params["bo"]
        return activations.get(self.activation)(y), state, new_carry

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = self.maybe_dropout(x, train=train, rng=rng)
        q = split_heads(x @ params["Wq"] + params["bq"], self.n_heads)
        k = split_heads(x @ params["Wk"] + params["bk"], self._kv_heads)
        v = split_heads(x @ params["Wv"] + params["bv"], self._kv_heads)
        if self.rope:
            if self.seq_axis is not None:
                # inside shard_map each chip holds global timesteps
                # [idx*T_local, (idx+1)*T_local)
                off = jax.lax.axis_index(self.seq_axis) * q.shape[1]
            else:
                off = 0
            positions = off + jnp.arange(q.shape[1])
            q = rope(q, positions, self.rope_theta)
            k = rope(k, positions, self.rope_theta)
        if self.seq_axis is not None:
            from deeplearning4j_tpu.parallel.sequence_parallel import ring_attention

            # the ring fold contracts GQA heads directly: the rotating K/V
            # keeps H_kv heads, preserving the ICI/memory shrink
            o = ring_attention(q, k, v, mask, axis_name=self.seq_axis,
                               causal=self.causal, window=self.window)
        else:
            o = None
            if self.flash and mask is None and q.dtype != jnp.float64:
                from deeplearning4j_tpu.helpers import get_helper

                helper = get_helper("attention")
                if helper is not None and helper.supports(q.shape[1],
                                                          q.shape[3]):
                    o = helper.attend(q, self._expand_kv(k),
                                      self._expand_kv(v), causal=self.causal,
                                      window=self.window)
            if o is None:
                # grouped contraction: no KV expansion materialized
                o = dot_product_attention(q, k, v, causal=self.causal,
                                          window=self.window, mask=mask)
        y = merge_heads(o) @ params["Wo"] + params["bo"]
        return activations.get(self.activation)(y), state
