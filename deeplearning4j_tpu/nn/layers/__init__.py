from deeplearning4j_tpu.nn.layers.base import Layer, register_layer, layer_from_dict
from deeplearning4j_tpu.nn.layers.dense import (
    DenseLayer,
    OutputLayer,
    ActivationLayer,
    DropoutLayer,
    EmbeddingLayer,
)
from deeplearning4j_tpu.nn.layers.convolution import (
    ConvolutionLayer,
    SubsamplingLayer,
    GlobalPoolingLayer,
)
from deeplearning4j_tpu.nn.layers.normalization import (
    BatchNormalization,
    LayerNorm,
    LocalResponseNormalization,
)
from deeplearning4j_tpu.nn.layers.attention import SelfAttentionLayer
from deeplearning4j_tpu.nn.layers.composite import ResidualBlock
from deeplearning4j_tpu.nn.layers.recurrent import (
    GravesLSTM,
    GravesBidirectionalLSTM,
    LSTM,
    RnnOutputLayer,
)
from deeplearning4j_tpu.nn.layers.autoencoder import AutoEncoder, RBM
from deeplearning4j_tpu.nn.layers.moe import MoELayer
