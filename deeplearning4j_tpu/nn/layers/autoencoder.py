"""Pretrain layers: denoising AutoEncoder and RBM with contrastive divergence.

Reference: ``nn/layers/feedforward/autoencoder/AutoEncoder.java`` (corruption +
reconstruction loss, tied weights with separate visible bias "vb") and
``nn/layers/feedforward/rbm/RBM.java:66-282`` (CD-k, Gibbs sampling,
binary/gaussian units).  The reference's stateful RNG Gibbs chains are
re-derived key-threaded (keys as explicit arguments), so pretraining jits and
remains reproducible — SURVEY.md §7 hard-part 6.

Both act as an encoder (dense forward) inside a supervised stack; their
unsupervised objective is exposed as ``pretrain_loss`` consumed by the model
facade's layerwise ``pretrain`` loop (reference ``MultiLayerNetwork.java:164``).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn import activations, initializers, losses
from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.nn.layers.base import Layer, register_layer


@register_layer
@dataclasses.dataclass(frozen=True)
class AutoEncoder(Layer):
    n_in: Optional[int] = None
    n_out: Optional[int] = None
    corruption_level: float = 0.3
    loss: str = "mse"  # reconstruction loss (reference RECONSTRUCTION_CROSSENTROPY or MSE)

    def setup(self, input_type: InputType) -> "AutoEncoder":
        if self.n_in is None:
            return dataclasses.replace(self, n_in=input_type.flat_size())
        return self

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.feed_forward(self.n_out)

    def init(self, key, dtype=jnp.float32):
        from deeplearning4j_tpu.nn.initializers import distribution_from_dict

        w = initializers.init(self.weight_init, key, (self.n_in, self.n_out), dtype,
                              distribution=distribution_from_dict(self.dist))
        return {
            "W": w,
            "b": jnp.full((self.n_out,), self.bias_init, dtype),
            "vb": jnp.zeros((self.n_in,), dtype),  # visible bias for decode
        }

    def encode(self, params, x):
        return activations.get(self.activation)(x @ params["W"] + params["b"])

    def decode(self, params, y):
        # tied weights: decoder = W^T (reference PretrainParamInitializer)
        return activations.get(self.activation)(y @ params["W"].T + params["vb"])

    def apply(self, params, state, x, *, train=False, rng=None):
        x = self.maybe_dropout(x, train=train, rng=rng)
        return self.encode(params, x), state

    def pretrain_loss(self, params, x, rng):
        if self.corruption_level > 0.0:
            k1, _ = jax.random.split(rng)
            keep = jax.random.bernoulli(k1, 1.0 - self.corruption_level, x.shape)
            x_in = jnp.where(keep, x, 0.0)
        else:
            x_in = x
        recon = self.decode(params, self.encode(params, x_in))
        return losses.score(self.loss, x, recon, "identity")


@register_layer
@dataclasses.dataclass(frozen=True)
class RBM(Layer):
    """Restricted Boltzmann machine trained by CD-k.

    hidden/visible unit kinds: "binary" | "gaussian" (reference HiddenUnit /
    VisibleUnit enums; RECTIFIED/SOFTMAX variants are gated behind the same
    field and can be added without API change).
    """

    n_in: Optional[int] = None
    n_out: Optional[int] = None
    hidden_unit: str = "binary"
    visible_unit: str = "binary"
    k: int = 1                      # Gibbs steps (CD-k)
    activation: str = "sigmoid"

    def setup(self, input_type: InputType) -> "RBM":
        if self.n_in is None:
            return dataclasses.replace(self, n_in=input_type.flat_size())
        return self

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.feed_forward(self.n_out)

    def init(self, key, dtype=jnp.float32):
        from deeplearning4j_tpu.nn.initializers import distribution_from_dict

        w = initializers.init(self.weight_init, key, (self.n_in, self.n_out), dtype,
                              distribution=distribution_from_dict(self.dist))
        return {
            "W": w,
            "b": jnp.zeros((self.n_out,), dtype),   # hidden bias
            "vb": jnp.zeros((self.n_in,), dtype),   # visible bias
        }

    def apply(self, params, state, x, *, train=False, rng=None):
        x = self.maybe_dropout(x, train=train, rng=rng)
        return self.prop_up(params, x), state

    def prop_up(self, params, v):
        pre = v @ params["W"] + params["b"]
        return jax.nn.sigmoid(pre) if self.hidden_unit == "binary" else pre

    def prop_down(self, params, h):
        pre = h @ params["W"].T + params["vb"]
        return jax.nn.sigmoid(pre) if self.visible_unit == "binary" else pre

    def _sample(self, key, probs, kind):
        if kind == "binary":
            return jax.random.bernoulli(key, probs).astype(probs.dtype)
        # gaussian units: mean + unit noise (reference Gaussian sampling)
        return probs + jax.random.normal(key, probs.shape, probs.dtype)

    def pretrain_loss(self, params, v0, rng):
        """CD-k free-energy surrogate.  The gradient of this scalar equals the
        CD update <v0 h0> - <vk hk> because the sampled chain is treated as
        constant (lax.stop_gradient), matching reference
        ``RBM.java:99`` contrastiveDivergence."""
        keys = jax.random.split(rng, 2 * self.k + 1)
        h_prob = self.prop_up(params, v0)
        h_sample = self._sample(keys[0], h_prob, self.hidden_unit)
        vk = v0
        hk = h_sample
        for i in range(self.k):
            vk_prob = self.prop_down(params, hk)
            vk = self._sample(keys[2 * i + 1], vk_prob, self.visible_unit)
            hk_prob = self.prop_up(params, vk)
            hk = self._sample(keys[2 * i + 2], hk_prob, self.hidden_unit)
        vk = jax.lax.stop_gradient(vk)
        # free energy F(v) = -v.vb - sum softplus(v W + b); CD grad = dF(v0) - dF(vk)
        return jnp.mean(self._free_energy(params, v0) - self._free_energy(params, vk))

    def _free_energy(self, params, v):
        pre = v @ params["W"] + params["b"]
        return -v @ params["vb"] - jnp.sum(jax.nn.softplus(pre), axis=-1)

    def reconstruction_error(self, params, v, rng):
        h = self.prop_up(params, v)
        recon = self.prop_down(params, h)
        return jnp.mean(jnp.sum((v - recon) ** 2, axis=-1))
