"""Composite layers: ResidualBlock (sequential sublayers + skip connection).

The reference expresses residual topology only through the ComputationGraph
ElementWiseVertex DAG (``nn/graph/vertex/impl/ElementWiseVertex.java``); this
composite gives the Sequential facade the same capability for uniform-width
blocks (transformers, ResNet-style MLPs) — XLA fuses the add into the
surrounding elementwise chain, so it costs nothing at runtime.

Sublayers must be shape-preserving end-to-end and stateless (LayerNorm,
SelfAttention, Dense are; BatchNorm is not — use the graph facade there).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.nn.layers.base import Layer, layer_from_dict, register_layer


@register_layer
@dataclasses.dataclass(frozen=True)
class ResidualBlock(Layer):
    """y = x + f(x) where f = sublayers applied in order.

    ``remat=True`` wraps f in ``jax.checkpoint``: activations inside the
    block are recomputed during the backward pass instead of stored —
    the standard long-context memory trade (activation memory per block
    drops from O(sublayers) to O(1) at ~1.3x FLOPs), composing with the
    sequence-parallel path for sequences that would not otherwise fit HBM."""

    layers: Tuple[Layer, ...] = ()
    remat: bool = False

    def setup(self, input_type: InputType) -> "ResidualBlock":
        done, it = [], input_type
        for sub in self.layers:
            sub = sub.setup(it)
            it = sub.output_type(it)
            done.append(sub)
        return dataclasses.replace(self, layers=tuple(done))

    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    def init(self, key, dtype=jnp.float32):
        ks = jax.random.split(key, max(len(self.layers), 1))
        params: Dict[str, Any] = {}
        for i, (sub, k) in enumerate(zip(self.layers, ks)):
            if sub.has_params():
                params[f"sub{i}"] = sub.init(k, dtype)
        return params

    def init_state(self):
        for sub in self.layers:
            if sub.init_state():
                raise ValueError(
                    "ResidualBlock sublayers must be stateless "
                    f"(got state from {type(sub).__name__})")
        return {}

    def _fused_prologue_helper(self, x):
        """The train-side fusion seam (roadmap item 1): a pre-norm block
        opens LayerNorm -> sublayer, i.e. the sublayer consumes
        ``dropout(LayerNorm(x))`` — exactly the fused
        dropout+residual+norm kernel's prologue form
        (``helpers/fused_epilogue.py``).  Returns the helper when the
        block shape and input qualify, else None (stock jnp path —
        which IS the parity reference)."""
        if len(self.layers) < 2:
            return None
        from deeplearning4j_tpu.nn.layers.normalization import LayerNorm

        ln = self.layers[0]
        if not isinstance(ln, LayerNorm) or ln.activation != "identity":
            return None
        from deeplearning4j_tpu.helpers import get_helper

        helper = get_helper("epilogue")
        if helper is None or not helper.supports(x):
            return None
        return helper

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        import inspect

        rngs = (jax.random.split(rng, len(self.layers))
                if rng is not None else [None] * len(self.layers))
        fused = self._fused_prologue_helper(x)

        def body(params, x, rngs, mask):
            h = x
            start = 0
            if fused is not None:
                ln, sub1 = self.layers[0], self.layers[1]
                # fold sub1's INPUT dropout (reference applyDropout
                # semantics — see Layer.maybe_dropout) into the fused
                # norm; the mask key is sub1's own rng, so the drawn
                # mask is bit-identical to the unfused path's
                rate = (sub1.dropout if train and sub1.dropout > 0.0
                        and not sub1.drop_connect else 0.0)
                h = fused.prologue(
                    h, params["sub0"]["gamma"], params["sub0"]["beta"],
                    eps=ln.eps, rate=rate, rng=rngs[1], train=train)
                sub1r = (dataclasses.replace(sub1, dropout=0.0)
                         if rate > 0.0 else sub1)
                kw = ({"mask": mask} if mask is not None and "mask" in
                      inspect.signature(sub1r.apply).parameters else {})
                h, _ = sub1r.apply(params.get("sub1", {}), {}, h,
                                   train=train, rng=rngs[1], **kw)
                start = 2
            for i in range(start, len(self.layers)):
                sub = self.layers[i]
                kw = ({"mask": mask} if mask is not None
                      and "mask" in inspect.signature(sub.apply).parameters else {})
                h, _ = sub.apply(params.get(f"sub{i}", {}), {}, h,
                                 train=train, rng=rngs[i], **kw)
            return x + h

        if self.remat and train:
            body = jax.checkpoint(body)
        return body(params, x, rngs, mask), state

    def init_cache(self, batch: int, dtype=jnp.float32):
        """Streaming carries for cache-bearing sublayers (attention KV
        caches).  Returns a dict (possibly empty) whenever ANY sublayer is
        carryable — recurrent sublayers seed their own state on first
        apply_with_carry(None), but the block must enter the carry path for
        that to happen — and None when the block holds none."""
        carry = {}
        carryable = False
        for i, sub in enumerate(self.layers):
            if hasattr(sub, "init_cache"):
                carryable = True
                c = sub.init_cache(batch, dtype)
                if c is not None:
                    carry[f"sub{i}"] = c
            elif hasattr(sub, "apply_with_carry"):
                carryable = True
        return carry if carryable else None

    def init_paged_cache(self, num_pages: int, page_size: int,
                         dtype=jnp.float32):
        """Paged-pool carries for pageable sublayers (attention KV pools —
        see ``SelfAttentionLayer.init_paged_cache``).  A sublayer that is
        carryable but NOT pageable (recurrent state) makes the whole block
        unpageable: the continuous-batching engine needs every carry to be
        slot-addressable through the block table, and recurrent hidden
        state is not — it raises so the engine fails loudly at setup."""
        carry = {}
        pageable = False
        for i, sub in enumerate(self.layers):
            if hasattr(sub, "init_paged_cache"):
                pageable = True
                c = sub.init_paged_cache(num_pages, page_size, dtype)
                if c is not None:
                    carry[f"sub{i}"] = c
            elif hasattr(sub, "apply_with_carry"):
                raise ValueError(
                    f"ResidualBlock sublayer {type(sub).__name__} carries "
                    "state but has no paged-cache form; the generation "
                    "engine only serves fully pageable (attention-cached) "
                    "stacks")
        return carry if pageable else None

    def apply_with_carry(self, params, state, x, carry, *, train=False,
                         rng=None, mask=None):
        """carry=None -> exact ``apply`` (training/batch paths untouched).
        With a carry dict: thread each sublayer's cache through; remat is
        irrelevant here (streaming is forward-only)."""
        if carry is None:
            y, st = self.apply(params, state, x, train=train, rng=rng,
                               mask=mask)
            return y, st, None
        import inspect

        rngs = (jax.random.split(rng, len(self.layers))
                if rng is not None else [None] * len(self.layers))
        h = x
        new_carry = {}
        for i, sub in enumerate(self.layers):
            p = params.get(f"sub{i}", {})
            if hasattr(sub, "apply_with_carry"):
                # thread the seeded cache (attention) or None (recurrent
                # sublayers initialize their own state and return it — they
                # must NOT be applied statelessly here, or their hidden
                # state would reset every streamed chunk)
                h, _, nc = sub.apply_with_carry(
                    p, {}, h, carry.get(f"sub{i}"), train=train,
                    rng=rngs[i], mask=mask)
                if nc is not None:
                    new_carry[f"sub{i}"] = nc
            else:
                kw = ({"mask": mask} if mask is not None
                      and "mask" in inspect.signature(sub.apply).parameters
                      else {})
                h, _ = sub.apply(p, {}, h, train=train, rng=rngs[i], **kw)
        return x + h, state, new_carry

    def reg_score(self, params):
        total = jnp.zeros(())
        for i, sub in enumerate(self.layers):
            if sub.has_params():
                total = total + sub.reg_score(params[f"sub{i}"])
        return total

    def to_dict(self) -> Dict[str, Any]:
        return {
            "type": "ResidualBlock",
            "name": self.name,
            "remat": self.remat,
            "layers": [sub.to_dict() for sub in self.layers],
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ResidualBlock":
        return cls(name=d.get("name"), remat=d.get("remat", False),
                   layers=tuple(layer_from_dict(s) for s in d["layers"]))
