"""Config system: fluent builder DSL -> immutable config, JSON round-trip.

Reference: ``nn/conf/NeuralNetConfiguration.java:413-449`` (Builder with
defaults: activation "sigmoid", WeightInit.XAVIER, lr 0.1, Updater SGD,
OptimizationAlgorithm STOCHASTIC_GRADIENT_DESCENT), per-layer overrides,
Jackson JSON/YAML round-trip (``MultiLayerConfiguration.java:75-120``),
structural validation (``ComputationGraphConfiguration.java:211``).

The JSON document is this framework's canonical persistent config form —
the ``configuration.json`` member of checkpoint archives (see
``models/serialization.py``).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, List, Optional, Tuple

from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.nn.layers.base import Layer, layer_from_dict
from deeplearning4j_tpu.nn.preprocessors import (
    Preprocessor,
    auto_preprocessor,
    preproc_from_dict,
)


@dataclasses.dataclass(frozen=True)
class UpdaterConfig:
    """Updater + schedule hyperparameters (reference ``nn/conf/Updater.java``
    enum + lr/momentum schedule maps on the Builder)."""

    name: str = "sgd"  # sgd|adam|adamw|adagrad|adadelta|nesterovs|rmsprop|none
    learning_rate: float = 0.1
    momentum: float = 0.9          # nesterovs
    rho: float = 0.95              # adadelta
    rmsprop_decay: float = 0.95    # rmsprop (reference rmsDecay)
    adam_beta1: float = 0.9
    adam_beta2: float = 0.999
    epsilon: float = 1e-8
    # learning-rate decay policy (reference LearningRatePolicy enum)
    lr_policy: str = "none"        # none|exponential|inverse|step|poly|sigmoid|schedule|warmup_cosine
    lr_policy_decay_rate: float = 0.0
    lr_policy_steps: float = 1.0
    lr_policy_power: float = 1.0
    lr_policy_warmup_steps: float = 0.0   # warmup_cosine: linear ramp length
    lr_policy_min_fraction: float = 0.0   # warmup_cosine: floor fraction of base
    weight_decay: float = 0.0      # adamw: DECOUPLED decay coefficient
    lr_schedule: Optional[Dict[int, float]] = None     # iteration -> lr
    momentum_schedule: Optional[Dict[int, float]] = None
    # gradient clipping/normalization (reference GradientNormalization enum)
    gradient_normalization: str = "none"  # none|renormalize_l2_per_layer|renormalize_l2_per_param_type|clip_element_wise_absolute_value|clip_l2_per_layer|clip_l2_per_param_type
    gradient_normalization_threshold: float = 1.0

    def to_dict(self):
        d = dataclasses.asdict(self)
        if d["lr_schedule"]:
            d["lr_schedule"] = {str(k): v for k, v in d["lr_schedule"].items()}
        if d["momentum_schedule"]:
            d["momentum_schedule"] = {str(k): v for k, v in d["momentum_schedule"].items()}
        return d

    @staticmethod
    def from_dict(d):
        d = dict(d)
        for k in ("lr_schedule", "momentum_schedule"):
            if d.get(k):
                d[k] = {int(i): v for i, v in d[k].items()}
        return UpdaterConfig(**d)


@dataclasses.dataclass(frozen=True)
class TrainingStability:
    """Training-stability policy (engine: ``resilience/stability.py``).

    The policy is pure configuration — serialized with the network config
    so a checkpointed run resumes with the same guard semantics.  The
    reference's closest analogs are ``GradientNormalization`` (bounded
    updates) and ``InvalidScoreIterationTerminationCondition`` (die on
    NaN); this subsumes both with a device-side non-finite step guard
    (a poisoned step becomes a no-op, no host sync), optional dynamic
    loss scaling for low-precision compute, and a host-side divergence
    sentinel that escalates skip -> LR backoff -> checkpoint rewind.

    ``loss_scaling``: ``"none"`` | ``"dynamic"`` (grow-on-streak /
    halve-on-overflow, state carried in the jitted step and
    checkpointed) | ``"static"`` (fixed ``loss_scale``).
    ``check_every``: fit-loop boundaries between sentinel polls — the
    only host syncs the engine performs happen at these boundaries, so
    the per-step hot path stays sync-free.  ``nonfinite_streak``:
    non-finite steps within one poll window that count as sustained
    divergence.  ``spike_factor`` / ``spike_patience``: finite-loss
    spike detection vs the rolling healthy baseline.  ``lr_backoff``:
    multiplier applied to the (device-carried) LR scale on escalation.
    ``poison_evict_after``: poisoned averaging windows before a replica
    is handed to the ElasticController as a ``"poisoned"`` eviction.
    """

    skip_nonfinite: bool = True
    loss_scaling: str = "none"          # none | dynamic | static
    loss_scale: float = 2.0 ** 15
    loss_scale_factor: float = 2.0
    loss_scale_growth_interval: int = 200
    loss_scale_min: float = 1.0
    loss_scale_max: float = 2.0 ** 24
    check_every: int = 25
    spike_factor: float = 10.0
    spike_patience: int = 2
    nonfinite_streak: int = 4
    lr_backoff: float = 0.5
    rewind_cooldown_checks: int = 2
    poison_evict_after: int = 2

    def __post_init__(self):
        if self.loss_scaling not in ("none", "dynamic", "static"):
            raise ValueError(
                f"unsupported loss_scaling '{self.loss_scaling}' "
                "(use 'none', 'dynamic', or 'static')")
        if self.loss_scale <= 0 or self.loss_scale_min <= 0:
            raise ValueError("loss scales must be > 0")
        if self.loss_scale_factor <= 1.0:
            raise ValueError("loss_scale_factor must be > 1")
        if self.check_every < 1:
            raise ValueError("check_every must be >= 1")
        if not 0.0 < self.lr_backoff < 1.0:
            raise ValueError("lr_backoff must be in (0, 1)")

    def to_dict(self):
        return dataclasses.asdict(self)

    @staticmethod
    def from_dict(d):
        return TrainingStability(**d)


@dataclasses.dataclass(frozen=True)
class TrainingIntrospection:
    """Training-introspection policy (engine:
    ``observability/introspection.py``).

    The reference's headline observability feature was the web training
    UI fed by ``StatsListener``: per-layer weight/gradient/update/
    activation statistics — the diagnostics that catch vanishing or
    exploding gradients, dead units, and mistuned learning rates before
    a run is wasted.  This policy enables the one-XLA-program version:
    per-layer gradient norm, update norm, update:param ratio, and
    activation summaries (mean/std/fraction-zero) are computed INSIDE
    the jitted train step as one fused reduction pass per leaf, carried
    in a reserved ``__introspect__`` subtree of the updater state (the
    ``__stability__`` pattern) so they stack per replica, shard, donate,
    and checkpoint — zero host syncs on non-report steps, one batched
    device->host transfer per reporting interval, zero recompiles.

    ``collect_activations``: also summarize every layer's training
    activations (mean / std / fraction-zero for dead-unit detection).
    ``dead_eps``: an activation counts as "dead" when ``|a| <= dead_eps``
    (0.0 = exact zeros, the ReLU case).
    """

    collect_activations: bool = True
    dead_eps: float = 0.0

    def __post_init__(self):
        if self.dead_eps < 0:
            raise ValueError(f"dead_eps must be >= 0, got {self.dead_eps}")

    def to_dict(self):
        return dataclasses.asdict(self)

    @staticmethod
    def from_dict(d):
        return TrainingIntrospection(**d)


@dataclasses.dataclass(frozen=True)
class TrainingNumerics:
    """Precision-ledger policy (engine: ``observability/numerics.py``).

    Per-layer dynamic-range statistics — max-abs, exponent histogram,
    and the fraction of values that would underflow/overflow each
    candidate narrow format (bf16 / fp16 / fp8-e4m3 / int8 with a
    per-page scale) — for gradients, updater moments, and activations,
    computed inside the jitted train step (the ``__introspect__``
    pattern: one fused reduction pass per leaf, carried in a reserved
    ``__numerics__`` updater-state subtree, zero recompiles, one
    device->host transfer per reporting interval).  Harvested into the
    per-layer format-safety verdicts that gate the bf16/fp8 flip
    (ROADMAP item 3).

    ``collect_activations``: also measure every layer's training
    activations (the forward-pass half of the narrowing evidence).
    ``absorb_threshold``: a format verdict goes risky when more than
    this fraction of a tensor's nonzero values would underflow to zero
    or be absorbed below the format's mantissa at the tensor's own
    scale (or when ANY value overflows — that has no threshold).
    ``sample``: per-(component, layer) stride-sample budget for the
    fraction/histogram pass (max-abs is always an exact full pass, so
    the hard overflow flag and the absorption cutoff never depend on
    it); 0 = exact full-pass fractions.
    ``interval``: collect the ledger every N steps (``lax.cond`` gated
    in-graph — off-steps carry the previous snapshot through at the
    cost of one branch, on-steps pay the stats pass; both branches
    compile once).  The ledger is a snapshot read once per reporting
    window, so align this with the listener's reporting frequency;
    1 = collect every step.  The defaults keep the ledger under the
    bench's 5% step-overhead sentinel.
    """

    collect_activations: bool = True
    absorb_threshold: float = 0.5
    sample: int = 1024
    interval: int = 10

    def __post_init__(self):
        if not 0.0 < self.absorb_threshold <= 1.0:
            raise ValueError("absorb_threshold must be in (0, 1], got "
                             f"{self.absorb_threshold}")
        if self.sample < 0:
            raise ValueError(f"sample must be >= 0, got {self.sample}")
        if self.interval < 1:
            raise ValueError(f"interval must be >= 1, got {self.interval}")

    def to_dict(self):
        return dataclasses.asdict(self)

    @staticmethod
    def from_dict(d):
        return TrainingNumerics(**d)


@dataclasses.dataclass(frozen=True)
class MultiLayerConfiguration:
    """Completed, immutable network config (reference
    ``nn/conf/MultiLayerConfiguration.java``)."""

    layers: Tuple[Layer, ...]
    preprocessors: Dict[int, Preprocessor]
    input_type: Optional[InputType]
    updater: UpdaterConfig
    seed: int = 12345
    optimization_algo: str = "stochastic_gradient_descent"
    num_iterations: int = 1         # reference iterations-per-minibatch default 1 (hot loop count)
    backprop_type: str = "standard"  # standard | truncated_bptt
    tbptt_fwd_length: int = 20
    tbptt_back_length: int = 20
    pretrain: bool = False
    backprop: bool = True
    # mixed precision: forward/backward compute dtype ("bfloat16"); params,
    # loss and updater math stay float32 (MXU-native policy; no reference
    # analog — ND4J is float-global)
    compute_dtype: Optional[str] = None
    # training-stability engine (non-finite step guard, loss scaling,
    # divergence sentinel) — None keeps the exact pre-stability trace
    stability: Optional[TrainingStability] = None
    # training-introspection engine (device-side per-layer gradient/
    # update/activation statistics) — None keeps the exact prior trace
    introspection: Optional[TrainingIntrospection] = None
    # precision-ledger engine (device-side per-layer dynamic-range /
    # format-safety statistics) — None keeps the exact prior trace
    numerics: Optional[TrainingNumerics] = None

    def __post_init__(self):
        # guard every construction path (builder, from_dict, direct): an
        # unknown compute dtype would silently cast params to garbage
        if self.compute_dtype not in (None, "bfloat16", "float16"):
            raise ValueError(
                f"unsupported compute_dtype '{self.compute_dtype}' "
                "(use 'bfloat16', 'float16', or None)")

    # ---- serde ----------------------------------------------------------
    def to_dict(self) -> Dict[str, Any]:
        return {
            "format_version": 1,
            "layers": [l.to_dict() for l in self.layers],
            "preprocessors": {str(i): p.to_dict() for i, p in self.preprocessors.items()},
            "input_type": self.input_type.to_dict() if self.input_type else None,
            "updater": self.updater.to_dict(),
            "seed": self.seed,
            "optimization_algo": self.optimization_algo,
            "num_iterations": self.num_iterations,
            "backprop_type": self.backprop_type,
            "tbptt_fwd_length": self.tbptt_fwd_length,
            "tbptt_back_length": self.tbptt_back_length,
            "pretrain": self.pretrain,
            "backprop": self.backprop,
            "compute_dtype": self.compute_dtype,
            "stability": self.stability.to_dict() if self.stability else None,
            "introspection": (self.introspection.to_dict()
                              if self.introspection else None),
            "numerics": self.numerics.to_dict() if self.numerics else None,
        }

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2)

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "MultiLayerConfiguration":
        return MultiLayerConfiguration(
            layers=tuple(layer_from_dict(ld) for ld in d["layers"]),
            preprocessors={int(i): preproc_from_dict(pd) for i, pd in d["preprocessors"].items()},
            input_type=InputType.from_dict(d["input_type"]) if d.get("input_type") else None,
            updater=UpdaterConfig.from_dict(d["updater"]),
            seed=d["seed"],
            optimization_algo=d["optimization_algo"],
            num_iterations=d["num_iterations"],
            backprop_type=d["backprop_type"],
            tbptt_fwd_length=d["tbptt_fwd_length"],
            tbptt_back_length=d["tbptt_back_length"],
            pretrain=d.get("pretrain", False),
            backprop=d.get("backprop", True),
            compute_dtype=d.get("compute_dtype"),
            stability=(TrainingStability.from_dict(d["stability"])
                       if d.get("stability") else None),
            introspection=(TrainingIntrospection.from_dict(d["introspection"])
                           if d.get("introspection") else None),
            numerics=(TrainingNumerics.from_dict(d["numerics"])
                      if d.get("numerics") else None),
        )

    @staticmethod
    def from_json(s: str) -> "MultiLayerConfiguration":
        return MultiLayerConfiguration.from_dict(json.loads(s))

    def to_yaml(self) -> str:
        """YAML form (reference ``MultiLayerConfiguration.toYaml`` :75)."""
        import yaml

        return yaml.safe_dump(self.to_dict(), sort_keys=False)

    @staticmethod
    def from_yaml(s: str) -> "MultiLayerConfiguration":
        import yaml

        return MultiLayerConfiguration.from_dict(yaml.safe_load(s))


class ListBuilder:
    """Layer-stack builder (reference ``NeuralNetConfiguration.ListBuilder``)."""

    def __init__(self, parent: "Builder"):
        self._parent = parent
        self._layers: List[Layer] = []
        self._preprocessors: Dict[int, Preprocessor] = {}
        self._input_type: Optional[InputType] = None
        self._backprop_type = "standard"
        self._tbptt_fwd = 20
        self._tbptt_back = 20
        self._pretrain = False
        self._backprop = True
        self._compute_dtype: Optional[str] = None

    def compute_dtype(self, dtype: str) -> "ListBuilder":
        """Mixed precision: run forward/backward in `dtype` ("bfloat16");
        params, loss and the updater stay float32."""
        if dtype not in ("bfloat16", "float16", "float32"):
            raise ValueError(f"unsupported compute dtype '{dtype}'")
        self._compute_dtype = None if dtype == "float32" else dtype
        return self

    def layer(self, layer: Layer, index: Optional[int] = None) -> "ListBuilder":
        if index is not None and index != len(self._layers):
            raise ValueError(f"layers must be added in order; expected {len(self._layers)}, got {index}")
        self._layers.append(layer)
        return self

    def input_preprocessor(self, index: int, preproc: Preprocessor) -> "ListBuilder":
        self._preprocessors[index] = preproc
        return self

    def set_input_type(self, t: InputType) -> "ListBuilder":
        self._input_type = t
        return self

    def backprop_type(self, kind: str, fwd_length: int = 20, back_length: int = 20) -> "ListBuilder":
        self._backprop_type = kind
        self._tbptt_fwd = fwd_length
        self._tbptt_back = back_length
        return self

    def pretrain(self, flag: bool) -> "ListBuilder":
        self._pretrain = flag
        return self

    def backprop(self, flag: bool) -> "ListBuilder":
        self._backprop = flag
        return self

    def build(self) -> MultiLayerConfiguration:
        if not self._layers:
            raise ValueError("No layers added")
        p = self._parent
        layers: List[Layer] = []
        cur_type = self._input_type
        for i, layer in enumerate(self._layers):
            layer = p._apply_global_defaults(layer)
            if layer.name is None:
                layer = layer.with_name(f"layer_{i}")
            if cur_type is not None:
                if i not in self._preprocessors:
                    pre = auto_preprocessor(cur_type, layer)
                    if pre is not None:
                        self._preprocessors[i] = pre
                if i in self._preprocessors:
                    cur_type = self._preprocessors[i].output_type(cur_type)
                layer = layer.setup(cur_type)
                cur_type = layer.output_type(cur_type)
            else:
                # no input type: n_in must be fully specified by the user
                if getattr(layer, "n_in", 0) is None:
                    raise ValueError(
                        f"Layer {i} ({type(layer).__name__}) has no n_in and no "
                        f"input_type was set for inference"
                    )
            # validate AFTER setup so checks see inferred sizes
            layer.validate()
            layers.append(layer)
        return MultiLayerConfiguration(
            layers=tuple(layers),
            preprocessors=dict(self._preprocessors),
            input_type=self._input_type,
            updater=p._updater,
            seed=p._seed,
            optimization_algo=p._optimization_algo,
            num_iterations=p._num_iterations,
            backprop_type=self._backprop_type,
            tbptt_fwd_length=self._tbptt_fwd,
            tbptt_back_length=self._tbptt_back,
            pretrain=self._pretrain,
            backprop=self._backprop,
            compute_dtype=self._compute_dtype,
            stability=p._stability,
            introspection=p._introspection,
            numerics=p._numerics,
        )


class Builder:
    """Global-hyperparameter builder (reference
    ``NeuralNetConfiguration.Builder``).  Global activation/weight-init/l1/l2/
    dropout are applied to layers that did not override them."""

    def __init__(self):
        self._seed = 12345
        self._updater = UpdaterConfig()
        self._optimization_algo = "stochastic_gradient_descent"
        self._num_iterations = 1
        self._activation: Optional[str] = None
        self._weight_init: Optional[str] = None
        self._dist: Optional[dict] = None
        self._l1: Optional[float] = None
        self._l2: Optional[float] = None
        self._dropout: Optional[float] = None
        self._regularization = False
        self._stability: Optional[TrainingStability] = None
        self._introspection: Optional[TrainingIntrospection] = None
        self._numerics: Optional[TrainingNumerics] = None

    def seed(self, s: int) -> "Builder":
        self._seed = int(s)
        return self

    def updater(self, name: str, **kwargs) -> "Builder":
        self._updater = dataclasses.replace(self._updater, name=name.lower(), **kwargs)
        return self

    def learning_rate(self, lr: float) -> "Builder":
        self._updater = dataclasses.replace(self._updater, learning_rate=lr)
        return self

    def momentum(self, m: float) -> "Builder":
        self._updater = dataclasses.replace(self._updater, momentum=m)
        return self

    def lr_policy(self, policy: str, **kwargs) -> "Builder":
        kw = {"lr_policy": policy}
        kw.update({f"lr_policy_{k}": v for k, v in kwargs.items()})
        self._updater = dataclasses.replace(self._updater, **kw)
        return self

    def lr_schedule(self, schedule: Dict[int, float]) -> "Builder":
        self._updater = dataclasses.replace(
            self._updater, lr_policy="schedule", lr_schedule=dict(schedule)
        )
        return self

    def gradient_normalization(self, kind: str, threshold: float = 1.0) -> "Builder":
        self._updater = dataclasses.replace(
            self._updater,
            gradient_normalization=kind,
            gradient_normalization_threshold=threshold,
        )
        return self

    def training_stability(self, policy=True, **kwargs) -> "Builder":
        """Enable the training-stability engine (device-side non-finite
        step guard, optional loss scaling, divergence sentinel — see
        ``TrainingStability`` / docs/resilience.md "Stability").  Pass a
        ``TrainingStability``, keyword overrides, or ``False`` to
        disable::

            .training_stability(loss_scaling="dynamic", check_every=10)
        """
        if policy is False or policy is None:
            if kwargs:
                raise ValueError("training_stability(False) takes no kwargs")
            self._stability = None
        elif isinstance(policy, TrainingStability):
            self._stability = (dataclasses.replace(policy, **kwargs)
                               if kwargs else policy)
        elif policy is True:
            self._stability = TrainingStability(**kwargs)
        else:
            raise ValueError(
                f"training_stability expects True/False/TrainingStability, "
                f"got {policy!r}")
        return self

    def training_introspection(self, policy=True, **kwargs) -> "Builder":
        """Enable the training-introspection engine (device-side
        per-layer gradient/update/activation statistics — see
        ``TrainingIntrospection`` / docs/observability.md "Training
        introspection").  Pass a ``TrainingIntrospection``, keyword
        overrides, or ``False`` to disable::

            .training_introspection(collect_activations=False)
        """
        if policy is False or policy is None:
            if kwargs:
                raise ValueError(
                    "training_introspection(False) takes no kwargs")
            self._introspection = None
        elif isinstance(policy, TrainingIntrospection):
            self._introspection = (dataclasses.replace(policy, **kwargs)
                                   if kwargs else policy)
        elif policy is True:
            self._introspection = TrainingIntrospection(**kwargs)
        else:
            raise ValueError(
                f"training_introspection expects True/False/"
                f"TrainingIntrospection, got {policy!r}")
        return self

    def training_numerics(self, policy=True, **kwargs) -> "Builder":
        """Enable the precision-ledger engine (device-side per-layer
        dynamic-range / format-safety statistics — see
        ``TrainingNumerics`` / docs/observability.md "Numerics").  Pass
        a ``TrainingNumerics``, keyword overrides, or ``False`` to
        disable::

            .training_numerics(absorb_threshold=0.25)
        """
        if policy is False or policy is None:
            if kwargs:
                raise ValueError("training_numerics(False) takes no kwargs")
            self._numerics = None
        elif isinstance(policy, TrainingNumerics):
            self._numerics = (dataclasses.replace(policy, **kwargs)
                              if kwargs else policy)
        elif policy is True:
            self._numerics = TrainingNumerics(**kwargs)
        else:
            raise ValueError(
                f"training_numerics expects True/False/TrainingNumerics, "
                f"got {policy!r}")
        return self

    def optimization_algo(self, algo: str) -> "Builder":
        self._optimization_algo = algo.lower()
        return self

    def iterations(self, n: int) -> "Builder":
        self._num_iterations = n
        return self

    def activation(self, a: str) -> "Builder":
        self._activation = a
        return self

    def weight_init(self, w: str, dist=None) -> "Builder":
        self._weight_init = w
        self._dist = dist.to_dict() if dist is not None and hasattr(dist, "to_dict") else dist
        return self

    def regularization(self, flag: bool) -> "Builder":
        self._regularization = flag
        return self

    def l1(self, v: float) -> "Builder":
        self._l1 = v
        return self

    def l2(self, v: float) -> "Builder":
        self._l2 = v
        return self

    def dropout(self, v: float) -> "Builder":
        self._dropout = v
        return self

    def list(self) -> ListBuilder:
        return ListBuilder(self)

    # graph() added by models/graph.py (ComputationGraph facade)
    def graph(self):
        from deeplearning4j_tpu.models.graph import GraphBuilder

        return GraphBuilder(self)

    def _apply_global_defaults(self, layer: Layer) -> Layer:
        """Push builder globals into layer fields that are at class default —
        the reference's layerwise-override semantics."""
        updates = {}
        for field, glob in (
            ("activation", self._activation),
            ("weight_init", self._weight_init),
            ("dist", self._dist),
            ("l1", self._l1 if self._regularization else None),
            ("l2", self._l2 if self._regularization else None),
            ("dropout", self._dropout),
        ):
            if glob is None or not hasattr(layer, field):
                continue
            cls_default = next(
                (f.default for f in dataclasses.fields(layer) if f.name == field), None
            )
            if getattr(layer, field) == cls_default:
                updates[field] = glob
        return dataclasses.replace(layer, **updates) if updates else layer


class NeuralNetConfiguration:
    @staticmethod
    def builder() -> Builder:
        return Builder()
