"""InputType — static shape metadata flowing through config.

Reference: ``nn/conf/inputs/InputType.java`` (FF/RNN/CNN variants) used for
layer n_in inference and preprocessor auto-insertion
(``nn/conf/layers/InputTypeUtil.java``, ``ConvolutionLayerSetup.java:42``).

TPU-first conventions (differ deliberately from the reference's ND4J layouts):
- feed-forward: [batch, size]
- recurrent:    [batch, time, size]        (reference: [batch, size, time])
- convolutional:[batch, height, width, ch] (reference NCHW; NHWC is the
  layout XLA tiles best onto the MXU/VPU)
Static shapes are load-bearing: every iterator pads/buckets so jit traces once.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple


@dataclasses.dataclass(frozen=True)
class InputType:
    kind: str  # "ff" | "rnn" | "cnn" | "cnn_flat"
    size: Optional[int] = None          # ff/rnn feature size
    timesteps: Optional[int] = None     # rnn known seq length (None = dynamic->padded)
    height: Optional[int] = None
    width: Optional[int] = None
    channels: Optional[int] = None

    @staticmethod
    def feed_forward(size: int) -> "InputType":
        return InputType("ff", size=size)

    @staticmethod
    def recurrent(size: int, timesteps: Optional[int] = None) -> "InputType":
        return InputType("rnn", size=size, timesteps=timesteps)

    @staticmethod
    def convolutional(height: int, width: int, channels: int) -> "InputType":
        return InputType("cnn", height=height, width=width, channels=channels)

    @staticmethod
    def convolutional_flat(height: int, width: int, channels: int) -> "InputType":
        """Flattened image rows (e.g. raw MNIST vectors), reference
        ``InputType.convolutionalFlat``."""
        return InputType(
            "cnn_flat",
            size=height * width * channels,
            height=height,
            width=width,
            channels=channels,
        )

    def flat_size(self) -> int:
        if self.kind in ("ff", "rnn", "cnn_flat"):
            return self.size
        return self.height * self.width * self.channels

    def batch_shape(self, batch: int) -> Tuple[int, ...]:
        if self.kind in ("ff", "cnn_flat"):
            return (batch, self.size)
        if self.kind == "rnn":
            return (batch, self.timesteps or 1, self.size)
        return (batch, self.height, self.width, self.channels)

    def to_dict(self):
        return {k: v for k, v in dataclasses.asdict(self).items() if v is not None}

    @staticmethod
    def from_dict(d) -> "InputType":
        return InputType(**d)
