"""Weight-init zoo — the reference's ``WeightInit`` enum re-derived.

Reference: ``nn/weights/WeightInit.java:33`` (DISTRIBUTION, ZERO, SIGMOID_UNIFORM,
UNIFORM, XAVIER, XAVIER_UNIFORM, XAVIER_FAN_IN, XAVIER_LEGACY, RELU, RELU_UNIFORM),
applied by ``nn/weights/WeightInitUtil.java``.  fan_in/fan_out follow the
reference convention: for a dense [n_in, n_out] kernel fan_in=n_in,
fan_out=n_out; for conv kernels fan_in = in_ch * prod(kernel),
fan_out = out_ch * prod(kernel).
"""

from __future__ import annotations

import math
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp


def _fans(shape: Sequence[int], fan_in: Optional[int], fan_out: Optional[int]) -> Tuple[int, int]:
    if fan_in is not None and fan_out is not None:
        return fan_in, fan_out
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv kernel HWIO: [kh, kw, in_ch, out_ch]
    receptive = math.prod(shape[:-2])
    return shape[-2] * receptive, shape[-1] * receptive


KNOWN = frozenset({
    "zero", "ones", "uniform", "xavier", "xavier_uniform", "xavier_fan_in",
    "xavier_legacy", "relu", "relu_uniform", "sigmoid_uniform", "normal",
    "distribution",
})


def check(name: str) -> None:
    if name.lower() not in KNOWN:
        raise ValueError(f"Unknown weight init '{name}'. Known: {sorted(KNOWN)}")


def init(
    name: str,
    key: jax.Array,
    shape: Sequence[int],
    dtype=jnp.float32,
    *,
    fan_in: Optional[int] = None,
    fan_out: Optional[int] = None,
    distribution=None,
):
    """Materialise a weight tensor using the named scheme."""
    name = name.lower()
    fi, fo = _fans(shape, fan_in, fan_out)
    shape = tuple(shape)

    if name == "zero":
        return jnp.zeros(shape, dtype)
    if name == "ones":
        return jnp.ones(shape, dtype)
    if name == "uniform":
        # reference: U(-a, a), a = 1/sqrt(fan_in)
        a = 1.0 / math.sqrt(fi)
        return jax.random.uniform(key, shape, dtype, -a, a)
    if name == "xavier":
        # reference XAVIER: gaussian, var = 2/(fan_in+fan_out)
        std = math.sqrt(2.0 / (fi + fo))
        return std * jax.random.normal(key, shape, dtype)
    if name == "xavier_uniform":
        a = math.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(key, shape, dtype, -a, a)
    if name == "xavier_fan_in":
        std = math.sqrt(1.0 / fi)
        return std * jax.random.normal(key, shape, dtype)
    if name == "xavier_legacy":
        std = math.sqrt(1.0 / (fi + fo))
        return std * jax.random.normal(key, shape, dtype)
    if name == "relu":
        # He init: gaussian, var = 2/fan_in
        std = math.sqrt(2.0 / fi)
        return std * jax.random.normal(key, shape, dtype)
    if name == "relu_uniform":
        a = math.sqrt(6.0 / fi)
        return jax.random.uniform(key, shape, dtype, -a, a)
    if name == "sigmoid_uniform":
        a = 4.0 * math.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(key, shape, dtype, -a, a)
    if name == "normal":
        return jax.random.normal(key, shape, dtype) / math.sqrt(fi)
    if name == "distribution":
        if distribution is None:
            raise ValueError("WeightInit 'distribution' requires a distribution spec")
        return distribution.sample(key, shape, dtype)
    raise ValueError(f"Unknown weight init '{name}'")


class NormalDistribution:
    """Custom-distribution spec (reference ``nn/conf/distribution/``)."""

    def __init__(self, mean: float = 0.0, std: float = 1.0):
        self.mean, self.std = mean, std

    def sample(self, key, shape, dtype):
        return self.mean + self.std * jax.random.normal(key, shape, dtype)

    def to_dict(self):
        return {"type": "normal", "mean": self.mean, "std": self.std}


class UniformDistribution:
    def __init__(self, lower: float = -1.0, upper: float = 1.0):
        self.lower, self.upper = lower, upper

    def sample(self, key, shape, dtype):
        return jax.random.uniform(key, shape, dtype, self.lower, self.upper)

    def to_dict(self):
        return {"type": "uniform", "lower": self.lower, "upper": self.upper}


def distribution_from_dict(d):
    if d is None:
        return None
    t = d["type"]
    if t == "normal":
        return NormalDistribution(d["mean"], d["std"])
    if t == "uniform":
        return UniformDistribution(d["lower"], d["upper"])
    raise ValueError(f"Unknown distribution type {t}")
