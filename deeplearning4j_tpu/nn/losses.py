"""Loss-function zoo.

Reference: ``LossFunctions.LossFunction`` used by output layers
(``nn/layers/BaseOutputLayer.java``) and gradient-checked exhaustively by
``LossFunctionGradientCheck.java``.  Every loss takes (labels, preoutput,
activation_name, mask) and returns per-example scores; gradients come from
``jax.grad`` over the mean score, replacing the reference's hand-derived
``LossFunction.computeGradient`` implementations.

Shapes: labels/preoutput are [batch, n_out] or [batch, time, n_out] for
sequences; mask broadcasts over the trailing feature dim.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn import activations

_EPS = 1e-8


def _activate(preout, activation: str):
    return activations.get(activation)(preout)


def mse(labels, preout, activation="identity", mask=None):
    out = _activate(preout, activation)
    per = jnp.sum((out - labels) ** 2, axis=-1)
    return _apply_mask(per, mask)


def l1(labels, preout, activation="identity", mask=None):
    out = _activate(preout, activation)
    per = jnp.sum(jnp.abs(out - labels), axis=-1)
    return _apply_mask(per, mask)


def l2(labels, preout, activation="identity", mask=None):
    # reference L2 = sum of squared errors (no 1/n)
    return mse(labels, preout, activation, mask)


def xent(labels, preout, activation="sigmoid", mask=None):
    """Binary cross-entropy (reference XENT)."""
    out = _activate(preout, activation)
    out = jnp.clip(out, _EPS, 1.0 - _EPS)
    per = -jnp.sum(labels * jnp.log(out) + (1 - labels) * jnp.log(1 - out), axis=-1)
    return _apply_mask(per, mask)


def mcxent(labels, preout, activation="softmax", mask=None):
    """Multi-class cross-entropy.  With softmax activation uses the fused
    log-softmax path (numerically stable, single XLA fusion)."""
    if activation == "softmax":
        logp = jax.nn.log_softmax(preout, axis=-1)
        per = -jnp.sum(labels * logp, axis=-1)
    else:
        out = jnp.clip(_activate(preout, activation), _EPS, 1.0)
        per = -jnp.sum(labels * jnp.log(out), axis=-1)
    return _apply_mask(per, mask)


def negativeloglikelihood(labels, preout, activation="softmax", mask=None):
    return mcxent(labels, preout, activation, mask)


def kl_divergence(labels, preout, activation="softmax", mask=None):
    out = jnp.clip(_activate(preout, activation), _EPS, 1.0)
    lab = jnp.clip(labels, _EPS, 1.0)
    per = jnp.sum(lab * (jnp.log(lab) - jnp.log(out)), axis=-1)
    return _apply_mask(per, mask)


def poisson(labels, preout, activation="identity", mask=None):
    out = jnp.clip(_activate(preout, activation), _EPS, None)
    per = jnp.sum(out - labels * jnp.log(out), axis=-1)
    return _apply_mask(per, mask)


def cosine_proximity(labels, preout, activation="identity", mask=None):
    out = _activate(preout, activation)
    num = jnp.sum(labels * out, axis=-1)
    den = jnp.linalg.norm(labels, axis=-1) * jnp.linalg.norm(out, axis=-1) + _EPS
    return _apply_mask(-num / den, mask)


def hinge(labels, preout, activation="identity", mask=None):
    out = _activate(preout, activation)
    per = jnp.sum(jnp.maximum(0.0, 1.0 - labels * out), axis=-1)
    return _apply_mask(per, mask)


def squared_hinge(labels, preout, activation="identity", mask=None):
    out = _activate(preout, activation)
    per = jnp.sum(jnp.maximum(0.0, 1.0 - labels * out) ** 2, axis=-1)
    return _apply_mask(per, mask)


def _apply_mask(per_example, mask):
    if mask is None:
        return per_example
    return per_example * mask


_REGISTRY: Dict[str, Callable] = {
    "mse": mse,
    "l1": l1,
    "l2": l2,
    "xent": xent,
    "mcxent": mcxent,
    "negativeloglikelihood": negativeloglikelihood,
    "kl_divergence": kl_divergence,
    "reconstruction_crossentropy": xent,
    "poisson": poisson,
    "cosine_proximity": cosine_proximity,
    "hinge": hinge,
    "squared_hinge": squared_hinge,
}


def get(name: str) -> Callable:
    try:
        return _REGISTRY[name.lower()]
    except KeyError:
        raise ValueError(f"Unknown loss '{name}'. Known: {sorted(_REGISTRY)}")


def score(name, labels, preout, activation, mask=None, mean=True):
    per = get(name)(labels, preout, activation, mask)
    if per.ndim > 1:  # time series [batch, time] -> sum over time
        per = jnp.sum(per, axis=tuple(range(1, per.ndim)))
    if not mean:
        return per
    if mask is not None:
        # masked mean: normalize by the number of unmasked timesteps
        denom = jnp.maximum(jnp.sum(mask), 1.0)
        return jnp.sum(per) / denom
    return jnp.mean(per)
