"""Input preprocessors — shape adapters auto-inserted between layers.

Reference: ``nn/conf/preprocessor/*.java`` (12 classes: CnnToFeedForward,
FeedForwardToCnn, FeedForwardToRnn, RnnToFeedForward, RnnToCnn, CnnToRnn...)
applied in ``MultiLayerNetwork.java:1139-1141`` forward and ``:1168-1170``
backward.  Functional core: each is a pure reshape; the backward epsilon
reshape the reference hand-writes comes free from autodiff.  Auto-insertion
logic lives in the config build (``MultiLayerConfiguration`` here), replacing
``ConvolutionLayerSetup.java:42``.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Type

import jax.numpy as jnp

from deeplearning4j_tpu.nn.inputs import InputType

_PREPROC_REGISTRY: Dict[str, Type["Preprocessor"]] = {}


def register_preproc(cls):
    _PREPROC_REGISTRY[cls.__name__] = cls
    return cls


def preproc_from_dict(d):
    d = dict(d)
    cls = _PREPROC_REGISTRY[d.pop("type")]
    return cls(**d)


@dataclasses.dataclass(frozen=True)
class Preprocessor:
    def __call__(self, x):
        raise NotImplementedError

    def output_type(self, input_type: InputType) -> InputType:
        raise NotImplementedError

    def to_dict(self):
        d = dataclasses.asdict(self)
        d["type"] = type(self).__name__
        return d


@register_preproc
@dataclasses.dataclass(frozen=True)
class CnnToFeedForward(Preprocessor):
    """[B,H,W,C] -> [B, H*W*C]."""

    def __call__(self, x):
        return x.reshape(x.shape[0], -1)

    def output_type(self, t: InputType) -> InputType:
        return InputType.feed_forward(t.flat_size())


@register_preproc
@dataclasses.dataclass(frozen=True)
class FeedForwardToCnn(Preprocessor):
    """[B, H*W*C] -> [B,H,W,C]."""

    height: int = 0
    width: int = 0
    channels: int = 1

    def __call__(self, x):
        return x.reshape(x.shape[0], self.height, self.width, self.channels)

    def output_type(self, t: InputType) -> InputType:
        return InputType.convolutional(self.height, self.width, self.channels)


@register_preproc
@dataclasses.dataclass(frozen=True)
class FeedForwardToRnn(Preprocessor):
    """[B*T, F] <- can't know T statically; here: [B, F] -> [B, 1, F] or pass
    through 3D. Used when stacking dense under recurrent layers."""

    def __call__(self, x):
        return x if x.ndim == 3 else x[:, None, :]

    def output_type(self, t: InputType) -> InputType:
        return InputType.recurrent(t.flat_size(), t.timesteps)


@register_preproc
@dataclasses.dataclass(frozen=True)
class RnnToFeedForward(Preprocessor):
    """[B,T,F] -> [B*T, F] (reference RnnToFeedForwardPreProcessor)."""

    def __call__(self, x):
        return x.reshape(-1, x.shape[-1])

    def output_type(self, t: InputType) -> InputType:
        return InputType.feed_forward(t.size)


@register_preproc
@dataclasses.dataclass(frozen=True)
class CnnToRnn(Preprocessor):
    """[B,H,W,C] -> [B, 1, H*W*C]."""

    def __call__(self, x):
        return x.reshape(x.shape[0], 1, -1)

    def output_type(self, t: InputType) -> InputType:
        return InputType.recurrent(t.flat_size(), 1)


@register_preproc
@dataclasses.dataclass(frozen=True)
class RnnToCnn(Preprocessor):
    """[B,T,H*W*C] -> [B*T,H,W,C]."""

    height: int = 0
    width: int = 0
    channels: int = 1

    def __call__(self, x):
        return x.reshape(-1, self.height, self.width, self.channels)

    def output_type(self, t: InputType) -> InputType:
        return InputType.convolutional(self.height, self.width, self.channels)


def auto_preprocessor(prev: InputType, layer) -> Optional[Preprocessor]:
    """Pick the adapter between ``prev`` output type and what ``layer`` expects
    (the ``InputTypeUtil``/``ConvolutionLayerSetup`` decision table)."""
    from deeplearning4j_tpu.nn.layers.convolution import ConvolutionLayer, SubsamplingLayer
    from deeplearning4j_tpu.nn.layers.dense import ActivationLayer, DropoutLayer
    from deeplearning4j_tpu.nn.layers.normalization import (
        BatchNormalization,
        LocalResponseNormalization,
    )
    from deeplearning4j_tpu.nn.layers.recurrent import (
        GravesLSTM,
        GravesBidirectionalLSTM,
        RnnOutputLayer,
    )

    # shape-preserving layers consume whatever the previous layer produced
    if isinstance(layer, (BatchNormalization, LocalResponseNormalization,
                          ActivationLayer, DropoutLayer)):
        return None

    wants_cnn = isinstance(layer, (ConvolutionLayer, SubsamplingLayer))
    wants_rnn = isinstance(layer, (GravesLSTM, GravesBidirectionalLSTM, RnnOutputLayer))

    if wants_cnn:
        if prev.kind == "cnn":
            return None
        if prev.kind in ("cnn_flat",):
            return FeedForwardToCnn(prev.height, prev.width, prev.channels)
        raise ValueError(f"Cannot feed {prev} into convolutional layer; use "
                         f"InputType.convolutional_flat for image vectors")
    if wants_rnn:
        if prev.kind == "rnn":
            return None
        if prev.kind in ("ff", "cnn_flat"):
            return FeedForwardToRnn()
        if prev.kind == "cnn":
            return CnnToRnn()
    # feed-forward consumer
    if prev.kind == "cnn":
        return CnnToFeedForward()
    return None
