"""Activation zoo.

Mirrors the reference's string-named activations (default "sigmoid",
``nn/conf/NeuralNetConfiguration.java:413-449``; dispatched through ND4J
transform ops).  Names are the reference's lowercase strings so configs
round-trip.  All functions are jit-safe elementwise ops that XLA fuses into
the surrounding matmul epilogue — no custom kernels needed (VPU work).
"""

from __future__ import annotations

from typing import Callable, Dict

import jax
import jax.numpy as jnp


def identity(x):
    return x


def sigmoid(x):
    return jax.nn.sigmoid(x)


def tanh(x):
    return jnp.tanh(x)


def relu(x):
    return jax.nn.relu(x)


def leakyrelu(x, alpha: float = 0.01):
    return jax.nn.leaky_relu(x, negative_slope=alpha)


def elu(x):
    return jax.nn.elu(x)


def softplus(x):
    return jax.nn.softplus(x)


def softsign(x):
    return jax.nn.soft_sign(x)


def hardtanh(x):
    return jnp.clip(x, -1.0, 1.0)


def hardsigmoid(x):
    return jnp.clip(0.2 * x + 0.5, 0.0, 1.0)


def cube(x):
    return x ** 3


def rationaltanh(x):
    # Reference "rationaltanh": 1.7159 * tanh(2x/3) rational approximation.
    return 1.7159 * jnp.tanh(2.0 * x / 3.0)


def softmax(x):
    return jax.nn.softmax(x, axis=-1)


def gelu(x):
    return jax.nn.gelu(x)


def swish(x):
    return jax.nn.silu(x)


_REGISTRY: Dict[str, Callable] = {
    "identity": identity,
    "linear": identity,
    "sigmoid": sigmoid,
    "tanh": tanh,
    "relu": relu,
    "leakyrelu": leakyrelu,
    "elu": elu,
    "softplus": softplus,
    "softsign": softsign,
    "hardtanh": hardtanh,
    "hardsigmoid": hardsigmoid,
    "cube": cube,
    "rationaltanh": rationaltanh,
    "softmax": softmax,
    "gelu": gelu,
    "swish": swish,
}


def get(name: str) -> Callable:
    try:
        return _REGISTRY[name.lower()]
    except KeyError:
        raise ValueError(f"Unknown activation '{name}'. Known: {sorted(_REGISTRY)}")


def register(name: str, fn: Callable) -> None:
    _REGISTRY[name.lower()] = fn
