"""Clustering + space-partition trees + t-SNE (≙ deeplearning4j-core
``clustering/`` and ``plot/``)."""

from deeplearning4j_tpu.clustering.kmeans import Cluster, ClusterSet, KMeansClustering
from deeplearning4j_tpu.clustering.trees import KDTree, QuadTree, SpTree, VPTree
from deeplearning4j_tpu.clustering.tsne import BarnesHutTsne, Tsne

__all__ = ["Cluster", "ClusterSet", "KMeansClustering", "KDTree", "QuadTree",
           "SpTree", "VPTree", "BarnesHutTsne", "Tsne"]
