"""K-means clustering.

Reference: ``deeplearning4j-core/.../clustering/kmeans/KMeansClustering.java``
+ the strategy/condition framework (``clustering/algorithm/strategy``,
``condition/``: iteration cap + distribution-variation convergence).

TPU redesign: Lloyd's algorithm as ONE jitted step — [N,K] distance matrix
on the MXU, argmin assignment, segment-sum centroid update — iterated under
``lax.while_loop`` with a centroid-shift convergence test, instead of the
reference's per-point Java loops.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np


@dataclass
class Cluster:
    """≙ ``clustering/cluster/Cluster.java`` (center + member points)."""

    center: np.ndarray
    point_indices: List[int] = field(default_factory=list)


@dataclass
class ClusterSet:
    """≙ ``clustering/cluster/ClusterSet.java``."""

    centers: np.ndarray          # [K, D]
    assignments: np.ndarray      # [N]
    inertia: float

    @property
    def clusters(self) -> List[Cluster]:
        return [Cluster(self.centers[k],
                        list(np.nonzero(self.assignments == k)[0]))
                for k in range(len(self.centers))]

    def nearest_cluster(self, point) -> int:
        d = ((self.centers - np.asarray(point)[None, :]) ** 2).sum(1)
        return int(np.argmin(d))


@partial(jax.jit, static_argnums=(2, 3))
def _lloyd(points, centers0, max_iterations, tol):
    """while centroid shift > tol: assign → recompute."""
    N, D = points.shape
    K = centers0.shape[0]

    def assign(centers):
        d = ((points[:, None, :] - centers[None, :, :]) ** 2).sum(-1)  # [N,K]
        return jnp.argmin(d, axis=1), d

    def body(state):
        centers, _, it, _ = state
        a, d = assign(centers)
        onehot = jax.nn.one_hot(a, K, dtype=points.dtype)              # [N,K]
        counts = onehot.sum(0)                                         # [K]
        sums = onehot.T @ points                                       # [K,D]
        new_centers = jnp.where(counts[:, None] > 0,
                                sums / jnp.maximum(counts[:, None], 1.0),
                                centers)
        shift = jnp.max(jnp.sum((new_centers - centers) ** 2, axis=1))
        return new_centers, a, it + 1, shift

    def cond(state):
        _, _, it, shift = state
        return jnp.logical_and(it < max_iterations, shift > tol)

    init = body((centers0, jnp.zeros(N, jnp.int32), jnp.asarray(0), jnp.inf))
    centers, a, it, shift = jax.lax.while_loop(cond, body, init)
    a, d = assign(centers)
    inertia = jnp.take_along_axis(d, a[:, None], 1).sum()
    return centers, a, inertia


class KMeansClustering:
    """≙ ``KMeansClustering.setup(k, maxIterations, distance)``."""

    def __init__(self, k: int, max_iterations: int = 100, tol: float = 1e-8,
                 seed: int = 12345):
        self.k = k
        self.max_iterations = max_iterations
        self.tol = tol
        self.seed = seed

    def apply_to(self, points) -> ClusterSet:
        points = jnp.asarray(np.asarray(points, np.float32))
        N = points.shape[0]
        if N < self.k:
            raise ValueError(f"k={self.k} > number of points {N}")
        # k-means++ style spread-out init (reference samples random points)
        rs = np.random.RandomState(self.seed)
        pts_np = np.asarray(points)
        first = rs.randint(N)
        chosen = [first]
        d2 = ((pts_np - pts_np[first]) ** 2).sum(1)
        for _ in range(self.k - 1):
            probs = d2 / max(d2.sum(), 1e-12)
            nxt = rs.choice(N, p=probs)
            chosen.append(int(nxt))
            d2 = np.minimum(d2, ((pts_np - pts_np[nxt]) ** 2).sum(1))
        centers0 = points[jnp.asarray(chosen)]
        centers, a, inertia = _lloyd(points, centers0,
                                     self.max_iterations, self.tol)
        return ClusterSet(np.asarray(centers), np.asarray(a), float(inertia))
