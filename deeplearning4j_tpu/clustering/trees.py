"""Space-partition trees: KD-tree, VP-tree, quad-tree, SP-tree.

Reference: ``deeplearning4j-core/.../clustering/kdtree/KDTree.java``,
``clustering/vptree/VPTree.java``, ``clustering/quadtree/QuadTree.java``,
``clustering/sptree/SpTree.java`` (the Barnes-Hut cell tree with centers of
mass).

These are host-side index structures (pointer-chasing is CPU work; on TPU
the bulk-distance path is a matmul — see ``wordvectors.words_nearest``), kept
for capability parity and for Barnes-Hut t-SNE.
"""

from __future__ import annotations

import heapq
from typing import List, Optional, Sequence, Tuple

import numpy as np


# ---------------------------------------------------------------- KD-tree

class _KDNode:
    __slots__ = ("idx", "dim", "left", "right")

    def __init__(self, idx, dim):
        self.idx = idx
        self.dim = dim
        self.left: Optional[_KDNode] = None
        self.right: Optional[_KDNode] = None


class KDTree:
    """Median-split k-d tree; insert/nn/knn. ≙ ``kdtree/KDTree.java``."""

    def __init__(self, points):
        self.points = np.asarray(points, np.float64)
        self.dims = self.points.shape[1]
        idxs = list(range(len(self.points)))
        self.root = self._build(idxs, 0)

    def _build(self, idxs: List[int], depth: int) -> Optional[_KDNode]:
        if not idxs:
            return None
        dim = depth % self.dims
        idxs.sort(key=lambda i: self.points[i, dim])
        mid = len(idxs) // 2
        node = _KDNode(idxs[mid], dim)
        node.left = self._build(idxs[:mid], depth + 1)
        node.right = self._build(idxs[mid + 1:], depth + 1)
        return node

    def nn(self, query) -> Tuple[int, float]:
        """Nearest neighbour: (index, distance)."""
        out = self.knn(query, 1)
        return out[0]

    def knn(self, query, k: int) -> List[Tuple[int, float]]:
        query = np.asarray(query, np.float64)
        heap: List[Tuple[float, int]] = []   # max-heap via negated dist

        def visit(node: Optional[_KDNode]):
            if node is None:
                return
            p = self.points[node.idx]
            d = float(np.linalg.norm(p - query))
            if len(heap) < k:
                heapq.heappush(heap, (-d, node.idx))
            elif d < -heap[0][0]:
                heapq.heapreplace(heap, (-d, node.idx))
            diff = query[node.dim] - p[node.dim]
            near, far = (node.left, node.right) if diff < 0 else (node.right, node.left)
            visit(near)
            if len(heap) < k or abs(diff) < -heap[0][0]:
                visit(far)

        visit(self.root)
        return sorted([( i, -nd) for nd, i in heap], key=lambda t: t[1])


# ---------------------------------------------------------------- VP-tree

class _VPNode:
    __slots__ = ("idx", "threshold", "inside", "outside")

    def __init__(self, idx):
        self.idx = idx
        self.threshold = 0.0
        self.inside: Optional[_VPNode] = None
        self.outside: Optional[_VPNode] = None


class VPTree:
    """Vantage-point tree (metric tree on euclidean distance).
    ≙ ``vptree/VPTree.java``."""

    def __init__(self, points, seed: int = 12345):
        self.points = np.asarray(points, np.float64)
        self._rs = np.random.RandomState(seed)
        self.root = self._build(list(range(len(self.points))))

    def _build(self, idxs: List[int]) -> Optional[_VPNode]:
        if not idxs:
            return None
        vp = idxs[self._rs.randint(len(idxs))]
        rest = [i for i in idxs if i != vp]
        node = _VPNode(vp)
        if not rest:
            return node
        dists = np.linalg.norm(self.points[rest] - self.points[vp], axis=1)
        median = float(np.median(dists))
        node.threshold = median
        inside = [i for i, d in zip(rest, dists) if d <= median]
        outside = [i for i, d in zip(rest, dists) if d > median]
        node.inside = self._build(inside)
        node.outside = self._build(outside)
        return node

    def knn(self, query, k: int) -> List[Tuple[int, float]]:
        query = np.asarray(query, np.float64)
        heap: List[Tuple[float, int]] = []

        def visit(node: Optional[_VPNode]):
            if node is None:
                return
            d = float(np.linalg.norm(self.points[node.idx] - query))
            if len(heap) < k:
                heapq.heappush(heap, (-d, node.idx))
            elif d < -heap[0][0]:
                heapq.heapreplace(heap, (-d, node.idx))
            tau = -heap[0][0] if len(heap) == k else np.inf
            if node.inside is None and node.outside is None:
                return
            if d < node.threshold:
                visit(node.inside)
                if d + tau >= node.threshold:
                    visit(node.outside)
            else:
                visit(node.outside)
                if d - tau <= node.threshold:
                    visit(node.inside)

        visit(self.root)
        return sorted([(i, -nd) for nd, i in heap], key=lambda t: t[1])


# --------------------------------------------------------------- quad-tree

class QuadTree:
    """2-D region quad-tree with per-cell center of mass.
    ≙ ``quadtree/QuadTree.java`` (the t-SNE 2-D special case)."""

    MAX_CAPACITY = 1

    def __init__(self, center_x, center_y, half_w, half_h):
        self.cx, self.cy = float(center_x), float(center_y)
        self.hw, self.hh = float(half_w), float(half_h)
        self.n_points = 0
        self.com = np.zeros(2)
        self.point: Optional[np.ndarray] = None
        self.children: Optional[List["QuadTree"]] = None

    @staticmethod
    def build(points) -> "QuadTree":
        pts = np.asarray(points, np.float64)
        lo, hi = pts.min(0), pts.max(0)
        c = (lo + hi) / 2
        half = max((hi - lo).max() / 2, 1e-9) * 1.001
        tree = QuadTree(c[0], c[1], half, half)
        for p in pts:
            tree.insert(p)
        return tree

    def contains(self, p) -> bool:
        return (abs(p[0] - self.cx) <= self.hw + 1e-12
                and abs(p[1] - self.cy) <= self.hh + 1e-12)

    def _subdivide(self):
        hw, hh = self.hw / 2, self.hh / 2
        self.children = [
            QuadTree(self.cx - hw, self.cy - hh, hw, hh),
            QuadTree(self.cx + hw, self.cy - hh, hw, hh),
            QuadTree(self.cx - hw, self.cy + hh, hw, hh),
            QuadTree(self.cx + hw, self.cy + hh, hw, hh),
        ]

    def insert(self, p) -> bool:
        p = np.asarray(p, np.float64)
        if not self.contains(p):
            return False
        self.com = (self.com * self.n_points + p) / (self.n_points + 1)
        self.n_points += 1
        if self.point is None and self.children is None:
            self.point = p
            return True
        # duplicate of the stored point: absorbed into the center of mass
        if self.point is not None and np.allclose(p, self.point):
            return True
        if self.children is None:
            self._subdivide()
            old = self.point
            self.point = None
            for ch in self.children:
                if ch.insert(old):
                    break
        for ch in self.children:
            if ch.insert(p):
                return True
        return False

    def depth(self) -> int:
        if self.children is None:
            return 1
        return 1 + max(ch.depth() for ch in self.children)


# ----------------------------------------------------------------- SP-tree

class SpTree:
    """k-d generalisation of the quad-tree (2^d children), with centers of
    mass — the Barnes-Hut acceleration structure.  ≙ ``sptree/SpTree.java``."""

    def __init__(self, center: np.ndarray, half: np.ndarray):
        self.center = np.asarray(center, np.float64)
        self.half = np.asarray(half, np.float64)
        self.d = len(self.center)
        self.n_points = 0
        self.com = np.zeros(self.d)
        self.point_idx: Optional[int] = None
        self.point: Optional[np.ndarray] = None
        self.children: Optional[List["SpTree"]] = None

    @staticmethod
    def build(points) -> "SpTree":
        pts = np.asarray(points, np.float64)
        lo, hi = pts.min(0), pts.max(0)
        c = (lo + hi) / 2
        half = np.maximum((hi - lo) / 2, 1e-9) * 1.001
        tree = SpTree(c, half)
        for i, p in enumerate(pts):
            tree.insert(p, i)
        return tree

    def contains(self, p) -> bool:
        return bool(np.all(np.abs(p - self.center) <= self.half + 1e-12))

    def _subdivide(self):
        self.children = []
        for mask in range(2 ** self.d):
            offset = np.array([(1 if (mask >> b) & 1 else -1)
                               for b in range(self.d)], np.float64)
            self.children.append(
                SpTree(self.center + offset * self.half / 2, self.half / 2))

    def insert(self, p, idx: int) -> bool:
        p = np.asarray(p, np.float64)
        if not self.contains(p):
            return False
        self.com = (self.com * self.n_points + p) / (self.n_points + 1)
        self.n_points += 1
        if self.point is None and self.children is None:
            self.point, self.point_idx = p, idx
            return True
        # duplicate of the stored point: absorbed into the center of mass
        # (≙ SpTree.java duplicate check — prevents infinite subdivision)
        if self.point is not None and np.allclose(p, self.point):
            return True
        if self.children is None:
            self._subdivide()
            old, old_idx = self.point, self.point_idx
            self.point = self.point_idx = None
            # identical duplicate points: keep in this cell's com only
            for ch in self.children:
                if ch.insert(old, old_idx):
                    break
        for ch in self.children:
            if ch.insert(p, idx):
                return True
        return False

    # Barnes-Hut accumulation of repulsive forces for t-SNE
    def compute_non_edge_forces(self, target: np.ndarray, theta: float,
                                neg_f: np.ndarray) -> float:
        """Adds this cell's contribution to ``neg_f``; returns its share of
        the normalisation sum Z.  ≙ ``SpTree.computeNonEdgeForces``."""
        if self.n_points == 0:
            return 0.0
        diff = target - self.com
        dist2 = float(diff @ diff)
        max_width = float(self.half.max() * 2)
        if self.children is None or (dist2 > 0 and
                                     max_width / np.sqrt(dist2) < theta):
            if self.n_points == 1 and dist2 == 0.0:
                return 0.0  # the target itself
            q = 1.0 / (1.0 + dist2)
            mult = self.n_points * q
            neg_f += mult * q * diff
            return mult
        z = 0.0
        for ch in self.children:
            z += ch.compute_non_edge_forces(target, theta, neg_f)
        return z
