"""t-SNE: exact (device) + Barnes-Hut (SpTree approximation).

Reference: ``deeplearning4j-core/.../plot/Tsne.java`` (exact gradient t-SNE
with momentum + gain adaptation) and ``plot/BarnesHutTsne.java:63,93,294``
(theta-approximated forces via SpTree, implemented as a ``Model``).

TPU redesign: the exact path runs the whole optimisation on device — the
[N,N] affinity/Q matrices are batched matmul/softmax shapes the MXU eats;
per-perplexity beta search is a vectorised bisection.  The Barnes-Hut path
stays host-side (pointer-chasing tree walk; reference parity) and is the
O(N log N) option for large N.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.clustering.trees import SpTree


# ---------------------------------------------------------------------------
# shared: P-matrix from perplexity (vectorised beta bisection)
# ---------------------------------------------------------------------------

def _p_conditional(dist2: np.ndarray, perplexity: float, tol: float = 1e-5,
                   max_tries: int = 50) -> np.ndarray:
    """Row-stochastic conditional affinities with per-row beta found by
    bisection so each row's entropy == log(perplexity)."""
    N = dist2.shape[0]
    target = np.log(perplexity)
    beta = np.ones(N)
    beta_min = np.full(N, -np.inf)
    beta_max = np.full(N, np.inf)
    mask = ~np.eye(N, dtype=bool)
    P = np.zeros((N, N))
    for _ in range(max_tries):
        expo = np.exp(-dist2 * beta[:, None])
        expo[~mask] = 0.0
        sums = np.maximum(expo.sum(1, keepdims=True), 1e-12)
        P = expo / sums
        # entropy per row
        H = -np.sum(np.where(P > 0, P * np.log(np.maximum(P, 1e-12)), 0.0), 1)
        diff = H - target
        done = np.abs(diff) < tol
        if done.all():
            break
        too_high = diff > 0  # entropy too high -> increase beta
        beta_min = np.where(too_high & ~done, beta, beta_min)
        beta_max = np.where(~too_high & ~done, beta, beta_max)
        beta = np.where(
            too_high & ~done,
            np.where(np.isinf(beta_max), beta * 2, (beta + beta_max) / 2),
            np.where(np.isinf(beta_min), beta / 2, (beta + beta_min) / 2))
    return P


def _joint_p(x: np.ndarray, perplexity: float) -> np.ndarray:
    d2 = ((x[:, None, :] - x[None, :, :]) ** 2).sum(-1)
    P = _p_conditional(d2, perplexity)
    P = (P + P.T) / (2 * len(x))
    return np.maximum(P, 1e-12)


# ---------------------------------------------------------------------------
# exact t-SNE — jitted update step
# ---------------------------------------------------------------------------

@partial(jax.jit, donate_argnums=(1, 2, 3))
def _tsne_step(P, y, vel, gains, lr, momentum):
    N = y.shape[0]
    d2 = ((y[:, None, :] - y[None, :, :]) ** 2).sum(-1)
    num = 1.0 / (1.0 + d2)
    num = num * (1.0 - jnp.eye(N, dtype=y.dtype))
    Q = jnp.maximum(num / jnp.maximum(num.sum(), 1e-12), 1e-12)
    PQ = (P - Q) * num                                   # [N,N]
    grad = 4.0 * ((jnp.diag(PQ.sum(1)) - PQ) @ y)        # [N,2]
    # gain adaptation (reference Tsne.java momentum/gain schedule)
    same_sign = jnp.sign(grad) == jnp.sign(vel)
    gains = jnp.maximum(jnp.where(same_sign, gains * 0.8, gains + 0.2), 0.01)
    vel = momentum * vel - lr * gains * grad
    y = y + vel
    y = y - y.mean(0, keepdims=True)
    kl = jnp.sum(P * jnp.log(P / Q))
    return y, vel, gains, kl


class Tsne:
    """Exact t-SNE. ≙ ``plot/Tsne.java`` builder knobs."""

    def __init__(self, n_components: int = 2, perplexity: float = 30.0,
                 learning_rate: float = 200.0, n_iter: int = 500,
                 momentum: float = 0.5, final_momentum: float = 0.8,
                 switch_momentum_iteration: int = 100,
                 early_exaggeration: float = 4.0,
                 stop_lying_iteration: int = 100, seed: int = 12345):
        self.n_components = n_components
        self.perplexity = perplexity
        self.learning_rate = learning_rate
        self.n_iter = n_iter
        self.momentum = momentum
        self.final_momentum = final_momentum
        self.switch_momentum_iteration = switch_momentum_iteration
        self.early_exaggeration = early_exaggeration
        self.stop_lying_iteration = stop_lying_iteration
        self.seed = seed
        self.kl_divergence_: Optional[float] = None

    def fit_transform(self, x) -> np.ndarray:
        x = np.asarray(x, np.float64)
        N = x.shape[0]
        P = _joint_p(x, min(self.perplexity, (N - 1) / 3.0))
        P_dev = jnp.asarray(P * self.early_exaggeration, jnp.float32)
        rs = np.random.RandomState(self.seed)
        y = jnp.asarray(rs.randn(N, self.n_components).astype(np.float32) * 1e-2)
        vel = jnp.zeros_like(y)
        gains = jnp.ones_like(y)
        kl = None
        for it in range(self.n_iter):
            if it == self.stop_lying_iteration:
                P_dev = jnp.asarray(P, jnp.float32)
            mom = (self.momentum if it < self.switch_momentum_iteration
                   else self.final_momentum)
            y, vel, gains, kl = _tsne_step(P_dev, y, vel, gains,
                                           jnp.float32(self.learning_rate),
                                           jnp.float32(mom))
        self.kl_divergence_ = float(kl)
        return np.asarray(y)


class BarnesHutTsne(Tsne):
    """theta-approximated t-SNE via SpTree (O(N log N) repulsion).
    ≙ ``plot/BarnesHutTsne.java`` (theta default 0.5)."""

    def __init__(self, theta: float = 0.5, **kw):
        super().__init__(**kw)
        self.theta = theta

    def fit_transform(self, x) -> np.ndarray:
        x = np.asarray(x, np.float64)
        N = x.shape[0]
        P = _joint_p(x, min(self.perplexity, (N - 1) / 3.0))
        # sparse-ish edges: keep 3*perplexity strongest per row (reference
        # uses exact kNN input similarities)
        rs = np.random.RandomState(self.seed)
        y = rs.randn(N, self.n_components) * 1e-2
        vel = np.zeros_like(y)
        gains = np.ones_like(y)
        P_work = P * self.early_exaggeration
        for it in range(self.n_iter):
            if it == self.stop_lying_iteration:
                P_work = P
            mom = (self.momentum if it < self.switch_momentum_iteration
                   else self.final_momentum)
            grad = self._gradient(P_work, y)
            same_sign = np.sign(grad) == np.sign(vel)
            gains = np.maximum(np.where(same_sign, gains * 0.8, gains + 0.2), 0.01)
            vel = mom * vel - self.learning_rate * gains * grad
            y = y + vel
            y -= y.mean(0, keepdims=True)
        # final KL (exact, for reporting)
        d2 = ((y[:, None, :] - y[None, :, :]) ** 2).sum(-1)
        num = 1.0 / (1.0 + d2)
        np.fill_diagonal(num, 0.0)
        Q = np.maximum(num / num.sum(), 1e-12)
        self.kl_divergence_ = float(np.sum(P * np.log(P / Q)))
        return y

    def _gradient(self, P: np.ndarray, y: np.ndarray) -> np.ndarray:
        N = y.shape[0]
        tree = SpTree.build(y)
        # attractive forces (edge forces): exact over nonzero P
        d2 = ((y[:, None, :] - y[None, :, :]) ** 2).sum(-1)
        qnum = 1.0 / (1.0 + d2)
        np.fill_diagonal(qnum, 0.0)
        pos = ((P * qnum)[:, :, None] * (y[:, None, :] - y[None, :, :])).sum(1)
        # repulsive via Barnes-Hut
        neg = np.zeros_like(y)
        Z = 0.0
        for i in range(N):
            f = np.zeros(y.shape[1])
            Z += tree.compute_non_edge_forces(y[i], self.theta, f)
            neg[i] = f
        return 4.0 * (pos - neg / max(Z, 1e-12))
