"""GenerationEngine: continuous-batching autoregressive serving.

Ties the pieces together into the decode analog of the PR-2
``ServingEngine``:

- a ``PagedKVCache`` + ``DecodeScheduler`` (iteration-level batching:
  requests join/leave the RUNNING batch every step; prefix sharing;
  admission control with 429/503/504 instead of hangs),
- a ``ModelRegistry`` (named/versioned models; ``deploy`` is a
  zero-drop hot-swap BETWEEN decode steps — in-flight streams keep
  their KV and continue under the new weights, which is the standard
  weight-only-update serving semantic),
- per-version ``GenerationPrograms`` (bucketed prefill + one decode
  step, AOT-warmed through the version's RecompileDetector before it
  serves: zero steady-state compiles),
- a ``GenerationMetrics`` bundle and ``step_guard`` spans (decode steps
  are visible to the StepProfiler/watchdog like any train step).

One background decode thread owns the device pools, the slot arrays,
and the page allocator; clients only touch the admission queue and
their own request handles, so ``submit``/``stream`` are thread-safe.

Minimal use::

    engine = GenerationEngine(net, slots=8, page_size=16,
                              max_context=128)
    engine.start()                      # AOT-warms every program
    h = engine.submit([1, 2, 3], max_new_tokens=16)
    for tok in h.stream(): ...          # tokens as they decode
    engine.deploy("default", new_net)   # hot-swap between steps
    engine.stop()
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Optional, Sequence

import jax
import numpy as np

from deeplearning4j_tpu.generation.paged_cache import PagedKVCache
from deeplearning4j_tpu.generation.prefix_cache import (
    PrefixCache, PrefixCacheConfig,
)
from deeplearning4j_tpu.generation.programs import GenerationPrograms
from deeplearning4j_tpu.generation.scheduler import (
    DecodeScheduler, GenerationRequest,
)
from deeplearning4j_tpu.observability.flightrecorder import (
    get_flight_recorder, step_guard,
)
from deeplearning4j_tpu.observability.fleet import SLOTracker
from deeplearning4j_tpu.observability.phases import PhaseTimers
from deeplearning4j_tpu.observability.servingmetrics import GenerationMetrics
from deeplearning4j_tpu.observability.tracing import get_tracer
from deeplearning4j_tpu.serving.admission import ModelNotFoundError
from deeplearning4j_tpu.serving.buckets import _pow2_buckets
from deeplearning4j_tpu.serving.registry import ModelRegistry, ModelVersion

logger = logging.getLogger("deeplearning4j_tpu.generation")

DEFAULT_MODEL = "default"

# finish reasons that count as a successful completion
_OK_REASONS = ("length", "stop")


class GenerationEngine:
    """See module docstring."""

    def __init__(self, model=None, *, slots: int = 8, page_size: int = 16,
                 max_context: int = 256, num_pages: Optional[int] = None,
                 max_queue: int = 64, deadline_s: float = 60.0,
                 prefill_buckets: Optional[Sequence[int]] = None,
                 models: Optional[ModelRegistry] = None, registry=None,
                 default_model: str = DEFAULT_MODEL,
                 prefix_cache=None, slo_targets: Optional[dict] = None,
                 decode_step_floor_s: float = 0.0):
        if max_context < 2:
            raise ValueError(f"max_context={max_context} must be >= 2")
        pages_per_slot = -(-int(max_context) // int(page_size))
        if num_pages is None:
            # default: full occupancy of every slot fits (+ trash page),
            # so admission only ever sheds on the queue budget
            num_pages = slots * pages_per_slot + 1
        self.metrics = GenerationMetrics(registry)
        # decode SLO attribution: TTFT/ITL attainment + goodput against
        # configurable targets (slo_targets={"ttft_target_s": ...,
        # "itl_target_s": ...}), federated via fleet_publisher()
        self.slo = SLOTracker(registry=self.metrics.registry,
                              engine_id=self.metrics.engine_id,
                              **(slo_targets or {}))
        # per-iteration phase breakdown of the decode loop (schedule /
        # page_gather / jitted_step / sample_harvest / stream_write)
        self.phases = PhaseTimers("generation_decode",
                                  registry=self.metrics.registry)
        self.busy_wall_s = 0.0          # decode-loop wall time, non-wait
        self.models = models or ModelRegistry(
            metrics_registry=self.metrics.registry)
        self.default_model = default_model
        self.cache = PagedKVCache(num_pages, page_size, pages_per_slot)
        # persistent radix-tree prefix cache (opt-in retention policy):
        # prefix_cache=True for defaults, a PrefixCacheConfig for knobs,
        # None/False keeps PR-13 free-on-release behavior bit-identical
        self.prefix_cache: Optional[PrefixCache] = None
        if prefix_cache:
            cfg = (prefix_cache if isinstance(prefix_cache,
                                              PrefixCacheConfig)
                   else PrefixCacheConfig())
            self.prefix_cache = PrefixCache(
                self.cache, host_budget_bytes=cfg.host_budget_bytes,
                metrics=self.metrics)
            self.cache.retention = self.prefix_cache
        self.scheduler = DecodeScheduler(
            self.cache, slots=slots, max_queue=max_queue,
            default_deadline_s=deadline_s, metrics=self.metrics)
        self.scheduler.on_finish = self._on_finish
        if prefill_buckets is None:
            prefill_buckets = _pow2_buckets(int(max_context))
        self.prefill_buckets = tuple(sorted(set(int(b)
                                                for b in prefill_buckets)))
        if model is not None:
            self.models.register(default_model, model)
        self._programs: "dict[str, GenerationPrograms]" = {}
        self._pools = None              # decode-thread-owned device state
        self._swap_lock = threading.Lock()
        self._stop_event = threading.Event()
        self._drain = True
        self._thread: Optional[threading.Thread] = None
        self.steady_deliveries = 0      # tokens delivered since start
        # device-simulation pacing: enforce a minimum wall time per
        # decode step.  On real accelerators the host thread mostly
        # WAITS on the device, so N replica processes scale across N
        # chips even on one host core; on the CPU tier the "device" IS
        # the host core and replicas contend instead.  The fleet bench
        # sets this to model the device-bound regime honestly (labeled
        # "paced" in its output); 0 disables and changes nothing.
        self.decode_step_floor_s = float(decode_step_floor_s)

    # ------------------------------------------------------------- lifecycle
    def start(self) -> "GenerationEngine":
        """Build + AOT-warm the active version's programs (every prefill
        bucket and the decode step compile NOW, through the version's
        RecompileDetector), allocate the live page pools, start the
        decode thread."""
        if self._thread is not None and self._thread.is_alive():
            raise RuntimeError("engine already started")
        mv = self.models.active(self.default_model)
        progs = self._build_programs(mv)
        self._pools = progs.fresh_pools()
        if self.prefix_cache is not None:
            # fresh pools mean every cached node points at garbage:
            # drop the tree, stamp the serving version, wire the page
            # transport + host-budget unit
            self.prefix_cache.invalidate("pool_reset")
            self.prefix_cache.set_version(mv.key)
            self.prefix_cache.attach(self,
                                     progs.page_nbytes(self._pools))
        self.scheduler.reopen()   # a restart re-arms admission
        from deeplearning4j_tpu.helpers import helpers_enabled
        from deeplearning4j_tpu.helpers.paged_attention import (
            paged_attention_mode)

        self.metrics.fused_attention.set(
            1.0 if helpers_enabled()
            and paged_attention_mode() == "fused" else 0.0)
        self._stop_event.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="generation-decode")
        self._thread.start()
        return self

    def stop(self, drain: bool = True, timeout: float = 30.0) -> None:
        """With ``drain`` (default) every queued and running request is
        still served (bounded by ``timeout``); without, queued requests
        fail 503 now and running ones are evicted at the next step
        boundary.  Either way no waiter is left hanging."""
        self._drain = drain
        self.scheduler.begin_shutdown(drain_pending=drain)
        self._stop_event.set()
        if self._thread is not None:
            self._thread.join(timeout)
            if self._thread.is_alive():
                logger.warning(
                    "decode thread still draining after %.1fs; failing "
                    "the remaining requests", timeout)
                self._drain = False
                self._thread.join(5.0)
        self._thread = None
        self.scheduler.evict_all("shutdown")
        # anything still queued after the drain window failed because the
        # ENGINE stopped, not because its own deadline passed: 503
        self.scheduler.begin_shutdown(drain_pending=False)
        self._refresh_gauges()

    # ---------------------------------------------------------------- submit
    def submit(self, prompt: Sequence[int], max_new_tokens: int = 32, *,
               temperature: float = 0.0, top_k: Optional[int] = None,
               top_p: Optional[float] = None, seed: int = 0,
               deadline_s: Optional[float] = None,
               stop_token: Optional[int] = None,
               trace_id: Optional[str] = None) -> GenerationRequest:
        """Thread-safe enqueue; returns the request handle (``stream()``
        for tokens as they decode, ``result()`` to block).  Raises
        ``QueueFullError`` (429) on a full queue, ``ShuttingDownError``
        (503) during shutdown, ``ValueError`` for a request that could
        never fit the page pool."""
        deadline = self.scheduler.admission.deadline_for(deadline_s)
        req = GenerationRequest(
            prompt, max_new_tokens, temperature=temperature, top_k=top_k,
            top_p=top_p, seed=seed, deadline_s=deadline,
            stop_token=stop_token, trace_id=trace_id)
        # worst case (no prefix shared) the WHOLE prompt prefills in one
        # bucket; reject here with a clean error instead of detonating a
        # ValueError on the decode thread mid-batch
        if len(req.prompt) > max(self.prefill_buckets):
            raise ValueError(
                f"prompt of {len(req.prompt)} tokens exceeds the largest "
                f"prefill bucket {max(self.prefill_buckets)}")
        return self.scheduler.submit(req)

    def generate(self, prompt: Sequence[int], max_new_tokens: int = 32,
                 **kw) -> np.ndarray:
        """Blocking convenience: submit + wait; returns the generated ids
        as a 1-D array."""
        req = self.submit(prompt, max_new_tokens, **kw)
        return np.asarray(req.result(), np.int32)

    # -------------------------------------------------------- prefix pinning
    def pin_prefix(self, prompt: Sequence[int]) -> int:
        """Pin ``prompt``'s cached prefix pages against offload and
        eviction (multi-turn sessions pin their history after each turn
        so the next turn only prefills the new tokens); returns a pin id
        for ``unpin_prefix``.  Thread-safe."""
        if self.prefix_cache is None:
            raise RuntimeError(
                "pin_prefix requires the persistent prefix cache "
                "(GenerationEngine(..., prefix_cache=True))")
        return self.prefix_cache.pin(prompt)

    def unpin_prefix(self, pin_id: int) -> None:
        """Release one pin; an unknown or already-released id raises
        ``KeyError``."""
        if self.prefix_cache is None:
            raise RuntimeError(
                "unpin_prefix requires the persistent prefix cache")
        self.prefix_cache.unpin(pin_id)

    # ------------------------------------------------- prefix-cache transport
    # PrefixCache calls these on the decode thread (inside admission,
    # which the engine's single decode loop drives), so reading and
    # replacing self._pools here is the owner thread acting.
    def cache_read_page(self, page: int):
        progs = self._programs[self.models.active(self.default_model).key]
        return progs.read_page(self._pools, page)

    def cache_write_page(self, page: int, payload) -> None:
        progs = self._programs[self.models.active(self.default_model).key]
        self._pools = progs.write_page(self._pools, page, payload)

    # ----------------------------------------------------------- model admin
    def deploy(self, name: str, model, *, retain_old: bool = False,
               drain_timeout: float = 30.0) -> ModelVersion:
        """Register ``model`` as the next version of ``name`` and hot-swap
        it in WITHOUT interrupting decode: the incoming version's
        programs are built and AOT-warmed first (a model that fails its
        warmup — or whose cache geometry differs from the live pools —
        aborts here with the old version intact), then the active
        pointer flips atomically; the decode loop leases per iteration,
        so the very next step runs the new weights while every in-flight
        stream keeps its slot, its pages, and its sampling state.  With
        ``retain_old`` the displaced version stays loaded as the
        ``rollback`` target."""
        if name != self.default_model:
            raise ValueError(
                f"generation engine serves one model name "
                f"({self.default_model!r}); decode batches cannot mix "
                f"models")
        with self._swap_lock:
            mv = self.models.new_version(name, model)
            self._build_programs(mv)   # raises -> swap aborted, old intact
            self._commit_locked(name, drain_timeout)
            old = self.models.activate(mv, retain=retain_old)
            get_flight_recorder().record(
                "generation_swap", model=name, version=mv.version,
                replaced=old.version if old else None,
                retained=bool(retain_old and old is not None))
            if old is not None:
                self.metrics.swaps.inc(model=name)
                if not retain_old:
                    self._retire(old, drain_timeout)
            logger.info("generation: %s now serving (replaced %s%s)",
                        mv.key, old.key if old else "nothing",
                        ", retained for rollback"
                        if retain_old and old else "")
            return mv

    def rollback(self, name: Optional[str] = None, *,
                 drain_timeout: float = 30.0) -> ModelVersion:
        """Undo the last retaining swap: flip back to the retained
        version between decode steps (its programs are still warm — a
        retained version's program set is only dropped at retire)."""
        name = name or self.default_model
        with self._swap_lock:
            restored, displaced = self.models.rollback(name)
            get_flight_recorder().record(
                "generation_rollback", model=name,
                restored=restored.version,
                displaced=displaced.version if displaced else None)
            self.metrics.swaps.inc(model=name)
            if displaced is not None:
                self._retire(displaced, drain_timeout)
            return restored

    def commit_swap(self, name: Optional[str] = None, *,
                    drain_timeout: float = 30.0) -> Optional[ModelVersion]:
        """Close the rollback window: retire the retained version."""
        with self._swap_lock:
            return self._commit_locked(name or self.default_model,
                                       drain_timeout)

    def _commit_locked(self, name: str, drain_timeout: float):
        mv = self.models.release_retained(name)
        if mv is not None:
            self._retire(mv, drain_timeout)
        return mv

    def _retire(self, mv: ModelVersion, timeout: float) -> None:
        if self.models.retire(mv, timeout=timeout):
            self._programs.pop(mv.key, None)   # drop its jit caches
        else:
            logger.warning("%s still leased after %.1fs; left un-retired",
                           mv.key, timeout)

    def _build_programs(self, mv: ModelVersion) -> GenerationPrograms:
        """Programs for one version, AOT-warmed on scratch pools, with
        the pool geometry validated against the live pools (a deploy
        whose architecture changes the KV shapes cannot share the
        in-flight cache and must be rejected)."""
        progs = GenerationPrograms(
            mv.model, slots=self.scheduler.num_slots,
            pages_per_slot=self.cache.pages_per_slot,
            page_size=self.cache.page_size, num_pages=self.cache.num_pages,
            prefill_buckets=self.prefill_buckets, detector=mv.detector)
        if self._pools is not None:
            live = jax.tree_util.tree_map(
                lambda a: (a.shape, str(a.dtype)), self._pools)
            new = jax.tree_util.tree_map(
                lambda a: (a.shape, str(a.dtype)),
                jax.eval_shape(progs.fresh_pools))
            if live != new:
                raise ValueError(
                    f"cannot deploy {mv.key}: its paged-cache geometry "
                    "differs from the live pools (layer names / kv heads "
                    "/ head dims must match the serving architecture)")
        progs.warm()
        self._programs[mv.key] = progs
        return progs

    # ------------------------------------------------------------ decode loop
    def _run(self) -> None:
        while True:
            stopping = self._stop_event.is_set()
            if stopping and (not self._drain
                             or not self.scheduler.has_work):
                break
            t_iter = time.perf_counter()
            with self.phases.phase("schedule"):
                self.scheduler.purge_pending()
            try:
                with self.models.lease(self.default_model) as mv:
                    progs = self._programs[mv.key]
                    if (self.prefix_cache is not None
                            and self.prefix_cache.version != mv.key):
                        # hot-swap/rollback observed: cached KV was
                        # prefilled under the displaced weights — a
                        # stale hit would be silently wrong, so the
                        # whole tree goes before any admission runs
                        n = self.prefix_cache.invalidate("swap")
                        self.prefix_cache.set_version(mv.key)
                        logger.info("prefix cache invalidated on swap "
                                    "to %s (%d nodes dropped)",
                                    mv.key, n)
                    self._admit(progs, mv)
                    if self.scheduler.active_slots():
                        self._step(progs, mv)
                        self.busy_wall_s += time.perf_counter() - t_iter
                        continue
            except Exception as e:
                logger.exception("decode iteration failed; evicting the "
                                 "running batch and reseeding the pools")
                get_flight_recorder().record("generation_error",
                                             error=str(e)[:200])
                self.scheduler.evict_all("error", e)
                try:
                    self._pools = self._programs[
                        self.models.active(self.default_model).key
                    ].fresh_pools()
                    if self.prefix_cache is not None:
                        # the reseed just zeroed every cached page
                        self.prefix_cache.invalidate("pool_reset")
                except Exception:
                    logger.exception("pool reseed failed; decode thread "
                                     "exiting")
                    return
            self.busy_wall_s += time.perf_counter() - t_iter
            if not stopping and not self.scheduler.has_work:
                self.scheduler.wait_for_work(0.05)

    def _admit(self, progs: GenerationPrograms, mv: ModelVersion) -> None:
        while True:
            with self.phases.phase("schedule"):
                req = self.scheduler.next_admittable()
            if req is None:
                return
            try:
                self._prefill(progs, mv, req)
            except Exception as e:
                # the request holds pages but no slot yet: evict_all in
                # the outer handler cannot see it, so terminate it here
                # (pages freed, waiters released, stale prefix-index
                # entries for its never-written pages removed) and let
                # the outer handler reset the pools
                self.scheduler.fail_admitted(req, e)
                raise

    def _prefill(self, progs: GenerationPrograms, mv: ModelVersion,
                 req: GenerationRequest) -> None:
        with self.phases.phase("page_gather"):
            suffix = req.prompt[req.shared_len:]
            bucket = progs.bucket_for(len(suffix))
            tokens = np.zeros((1, bucket), np.int32)
            tokens[0, :len(suffix)] = suffix
            shared_pages = req.shared_len // self.cache.page_size
            base_key = _base_key(req.seed)
            block = self.cache.block_row(req.pages)[None]
        with step_guard("decode_prefill", engine=self.metrics.engine_id,
                        bucket=bucket, shared_pages=shared_pages):
            with self.phases.phase("jitted_step"):
                self._pools, tok = progs.prefill(
                    bucket, mv.model.params, mv.model.net_state,
                    self._pools, block,
                    np.asarray([req.shared_len], np.int32),
                    np.int32(len(suffix) - 1), tokens, base_key[None],
                    np.zeros(1, np.int32),
                    np.asarray([req.temperature], np.float32),
                    np.asarray([req.top_k], np.int32),
                    np.asarray([req.top_p], np.float32))
        with self.phases.phase("sample_harvest"):
            first = int(jax.device_get(tok)[0])
        with self.phases.phase("stream_write"):
            self.scheduler.install(req, first, base_key)
            self.metrics.ttft.observe(req.ttft_s)
            self.metrics.prefix_pages.inc(shared_pages, outcome="shared")
            self.metrics.prefix_pages.inc(len(req.pages) - shared_pages,
                                          outcome="fresh")
            self.metrics.tokens.inc(model=mv.name)
            self._refresh_gauges()

    def _step(self, progs: GenerationPrograms, mv: ModelVersion) -> None:
        s = self.scheduler
        active = len(s.active_slots())
        t_step0 = time.perf_counter()
        with step_guard("decode_step", engine=self.metrics.engine_id,
                        active=active):
            with self.phases.phase("jitted_step"):
                self._pools, sampled = progs.decode(
                    mv.model.params, mv.model.net_state, self._pools,
                    s.block, s.pos, s.last_tok, s.keys, s.tok_idx,
                    s.temps, s.top_ks, s.top_ps)
        with self.phases.phase("sample_harvest"):
            sampled_host = jax.device_get(sampled)
        with self.phases.phase("stream_write"):
            delivered = s.after_step(sampled_host)
            self.steady_deliveries += delivered
            self.metrics.steps.inc()
            self.metrics.tokens.inc(delivered, model=mv.name)
            self.metrics.batch_occupancy.observe(active / s.num_slots)
            self._refresh_gauges()
        if self.decode_step_floor_s > 0.0:
            # sleep (not spin) to the floor: the yielded core is exactly
            # what lets sibling replica processes decode concurrently
            remain = self.decode_step_floor_s - (time.perf_counter()
                                                 - t_step0)
            if remain > 0:
                time.sleep(remain)

    def _refresh_gauges(self) -> None:
        self.metrics.active_slots.set(len(self.scheduler.active_slots()))
        self.metrics.page_util.set(self.cache.utilization())
        if self.prefix_cache is not None:
            # one locked snapshot — three separate reads could tear
            # across a concurrent eviction/offload (resident dropping
            # while host_bytes had not risen yet)
            st = self.prefix_cache.stats()
            self.metrics.prefix_cache_resident.set(st["resident_pages"])
            self.metrics.prefix_cache_pinned.set(st["pinned_pages"])
            self.metrics.prefix_cache_host_bytes.set(
                st["host_tier_bytes"])

    def _on_finish(self, req: GenerationRequest) -> None:
        """Terminal accounting for every request, whatever path ended it
        (completion, stop token, cancel, deadline, shutdown, error)."""
        status = req.finish_reason or "error"
        self.metrics.requests.inc(status=status)
        # SLO verdict BEFORE the waiters wake (scheduler calls on_finish
        # before releasing them), so access logs and req.as_dict() read
        # a settled slo_ok
        req.slo_ok = self.slo.observe_request(
            ttft_s=req.ttft_s, itl_s=req.itl_s,
            completed=status in _OK_REASONS)
        end_ns = time.perf_counter_ns()
        start_ns = int(req.submitted * 1e9)
        get_tracer().record_span(
            "generation_request", start_ns, end_ns,
            trace_id=req.trace_id, tokens=len(req.tokens), status=status,
            ttft_ms=(round(req.ttft_s * 1e3, 3)
                     if req.ttft_s is not None else None),
            itl_p50_ms=req.itl_p50_ms(), slo_ok=req.slo_ok)

    def kv_numerics(self, allocated_only: bool = True) -> dict:
        """Per-page dynamic-range ledger over the live KV pools
        (``observability.numerics.kv_page_ledger``): the int8-KV
        quantization-readiness evidence, read from whatever pools the
        decode thread last published.  Pools are replaced (not mutated
        in place) by prefill/decode, so reading the reference from
        another thread is safe — at worst one step stale."""
        from deeplearning4j_tpu.observability import numerics
        pools = self._pools
        if pools is None:
            return {}
        allocated = None
        if allocated_only:
            allocated = [p for p in range(1, self.cache.num_pages)
                         if self.cache.refcount(p) > 0]
        return numerics.kv_page_ledger(
            pools, self.cache.page_size, allocated=allocated)

    # ----------------------------------------------------------------- stats
    def stats(self) -> dict:
        return {
            "model": self.default_model,
            "models": self.models.as_dict(),
            "scheduler": self.scheduler.as_dict(),
            "prefill_buckets": list(self.prefill_buckets),
            "decode_thread_alive": (self._thread is not None
                                    and self._thread.is_alive()),
            "phases": self.phases.as_dict(),
            "busy_wall_s": round(self.busy_wall_s, 6),
            "slo": self.slo.as_dict(),
        }

    def fleet_publisher(self, worker_id: str, **kw):
        """A ``TelemetryPublisher`` pre-wired to this engine: local
        registry, SLO tracker, one-locked-snapshot prefix-cache stats,
        and the scheduler state dict.  Caller supplies the transport
        (``broker=`` or ``url=``) and calls ``start()``.  Reads only
        host-side state — publishing never touches the device."""
        from deeplearning4j_tpu.observability.fleet import (
            TelemetryPublisher,
        )
        kw.setdefault("registry", self.metrics.registry)
        kw.setdefault("slo", self.slo)
        if self.prefix_cache is not None:
            kw.setdefault("prefix_cache", self.prefix_cache)
        kw.setdefault("state_fn",
                      lambda: {"scheduler": self.scheduler.as_dict()})
        return TelemetryPublisher(worker_id, **kw)

    def cache_stats(self) -> dict:
        """The ``GET /generation/cache`` payload: allocator occupancy
        plus the persistent prefix cache's tree/host-tier stats (null
        when running the legacy free-on-release policy)."""
        return {
            "cache": self.cache.as_dict(),
            "prefix_cache": (self.prefix_cache.stats()
                             if self.prefix_cache is not None else None),
        }


def _base_key(seed: int) -> np.ndarray:
    """A request's raw uint32 base PRNG key (host copy; folded per token
    index on device — see ``utils.sampling.sample_tokens``)."""
    return np.asarray(jax.device_get(jax.random.PRNGKey(seed)), np.uint32)
