"""Compiled generation programs: bucketed prefill + ONE decode step.

The whole engine dispatches exactly ``len(prefill_buckets) + 3`` XLA
programs per model version, all AOT-warmed before the version serves:

- ``prefill_<bucket>``: one request's (non-shared) prompt suffix, padded
  up to the bucket length, forwarded through the paged carries in a
  single [1, bucket] call — writes its K/V into the request's pages and
  samples the first token from the last REAL prompt position's logits.
- ``decode``: one token for EVERY slot in a single [slots, 1] call —
  the iteration-level batch.  Idle slots ride along pointed at the
  trash page with temperature 0; their lanes are pure garbage-in/
  garbage-out and the scheduler ignores their outputs.
- ``read_page`` / ``write_page``: one page's K/V slice out of / into
  every pool — the prefix cache's host-tier transport.

Shapes are closed by construction (slot count, pool size, block-table
width, bucket lengths are all fixed at engine construction), so steady
state compiles exactly nothing — proven through the version's
``RecompileDetector`` the same way the PR-2 serving warmup proves it.

KV pools are donated on every call: XLA writes the new K/V in place
instead of copying pool-sized buffers per token.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.models.decode import (
    _cg_single_io, _ids_need_time_axis, _last_logits_fwd,
)
from deeplearning4j_tpu.utils.sampling import _resolve_encoding, sample_tokens


def named_layers_of(net) -> List[Tuple[str, object]]:
    """(name, layer) pairs for either facade — the walk
    ``models.decode.generate`` uses, shared here for pool seeding."""
    from deeplearning4j_tpu.models.sequential import MultiLayerNetwork

    if isinstance(net, MultiLayerNetwork):
        return [(l.name, l) for l in net.layers]
    _cg_single_io(net)   # generation feeds back ONE token stream
    return [(n, net.nodes[n].layer) for n in net.topo
            if net.nodes[n].layer is not None]


def seed_paged_pools(net, num_pages: int, page_size: int,
                     dtype=None) -> Dict:
    """Paged KV pools for every pageable layer of ``net`` (the paged
    analog of ``models.common.seed_stream_caches``).  Raises when the
    net carries state that cannot be paged (recurrent hidden state) —
    the engine must fail at setup, not serve wrong tokens."""
    cache_dtype = (jnp.dtype(dtype) if dtype else jnp.float32)
    pools = {}
    for name, layer in named_layers_of(net):
        if hasattr(layer, "init_paged_cache"):
            c = layer.init_paged_cache(num_pages, page_size, cache_dtype)
            if c is not None:
                pools[name] = c
        elif hasattr(layer, "apply_with_carry"):
            raise ValueError(
                f"layer '{name}' ({type(layer).__name__}) carries "
                "non-pageable state; the generation engine only serves "
                "attention-cached (transformer) stacks")
    if not pools:
        raise ValueError(
            "no pageable attention layers found — the generation engine "
            "needs at least one causal SelfAttentionLayer KV cache")
    return pools


def _attach(pools, block, pos):
    """Insert the dispatch's block table / positions into every paged
    leaf (the pool pytree stays pk/pv-only between dispatches)."""
    def walk(c):
        if isinstance(c, dict) and "pk" in c:
            return {**c, "block": block, "pos": pos}
        if isinstance(c, dict):
            return {k: walk(v) for k, v in c.items()}
        return c
    return {k: walk(v) for k, v in pools.items()}


def _strip(carries):
    """Keep only the updated pools out of the forward's new carries.
    The forward returns a carry entry for EVERY carry-capable layer —
    ``None`` for the ones that ran carry-less (MLP residual blocks) —
    and those must be dropped, or the output pytree's structure would
    differ from the input pools' and every warmed program would retrace
    on its first live call."""
    def walk(c):
        if isinstance(c, dict) and "pk" in c:
            return {"pk": c["pk"], "pv": c["pv"]}
        if isinstance(c, dict):
            out = {k: w for k, v in c.items()
                   if (w := walk(v)) is not None}
            return out or None
        return None
    return {k: w for k, v in (carries or {}).items()
            if (w := walk(v)) is not None}


class GenerationPrograms:
    """The jitted program set for ONE model version (the engine builds a
    fresh set per deploy and AOT-warms it before the version serves)."""

    def __init__(self, net, *, slots: int, pages_per_slot: int,
                 page_size: int, num_pages: int,
                 prefill_buckets: Tuple[int, ...], detector=None):
        self.net = net
        self.slots = int(slots)
        self.pages_per_slot = int(pages_per_slot)
        self.page_size = int(page_size)
        self.num_pages = int(num_pages)
        self.prefill_buckets = tuple(sorted(int(b) for b in prefill_buckets))
        self.detector = detector
        probe = np.zeros((1, 1), np.int64)
        _, self.one_hot, self.vocab_size = _resolve_encoding(
            net, probe, None, None)
        self.expand_ids = _ids_need_time_axis(net, self.one_hot)
        self._fwd = _last_logits_fwd(net)
        # validate pageability eagerly (raises on recurrent stacks)
        seed_paged_pools(net, 2, page_size, net.conf.compute_dtype)
        self._decode = jax.jit(self._make_decode(), donate_argnums=(2,))
        self._prefill = {
            b: jax.jit(self._make_prefill(b), donate_argnums=(2,))
            for b in self.prefill_buckets}
        # page transport (prefix-cache host tier): one page's K/V slice
        # out of / into every pool.  Fixed shapes — two more members of
        # the closed program set, warmed with the rest.
        self._read_page = jax.jit(self._make_read_page())
        self._write_page = jax.jit(self._make_write_page(),
                                   donate_argnums=(0,))

    # ---------------------------------------------------------------- build
    def fresh_pools(self):
        return seed_paged_pools(self.net, self.num_pages, self.page_size,
                                self.net.conf.compute_dtype)

    def bucket_for(self, length: int) -> int:
        for b in self.prefill_buckets:
            if length <= b:
                return b
        raise ValueError(
            f"prompt suffix of {length} tokens exceeds the largest "
            f"prefill bucket {self.prefill_buckets[-1]}")

    def _encode(self, tokens):
        if self.one_hot:
            return jax.nn.one_hot(tokens, self.vocab_size,
                                  dtype=jnp.float32)
        return tokens[..., None] if self.expand_ids else tokens

    def _make_decode(self):
        fwd, encode = self._fwd, self._encode

        def decode_step(params, net_state, pools, block, pos, tokens,
                        keys, token_idx, temps, top_ks, top_ps):
            """One token for every slot: [S] in, [S] out."""
            x = encode(tokens[:, None])
            pre, nc = fwd(params, net_state, x, _attach(pools, block, pos))
            logits = pre[:, -1].astype(jnp.float32)
            nxt = sample_tokens(logits, keys, token_idx, temps, top_ks,
                                top_ps)
            return _strip(nc), nxt.astype(jnp.int32)

        return decode_step

    def _make_prefill(self, bucket: int):
        fwd, encode = self._fwd, self._encode

        def prefill(params, net_state, pools, block, start, last_idx,
                    tokens, keys, token_idx, temps, top_ks, top_ps):
            """One request's prompt suffix ([1, bucket]) + first sample.
            ``start`` [1] is the suffix's global start position (0, or
            the shared-prefix length); ``last_idx`` () indexes the last
            REAL token inside the bucket — bucket padding beyond it
            writes scratch K/V that the causal mask hides until decode
            overwrites it position by position."""
            x = encode(tokens)
            pre, nc = fwd(params, net_state, x,
                          _attach(pools, block, start))
            logits = jnp.take(pre[0], last_idx, axis=0)[None]
            tok = sample_tokens(logits.astype(jnp.float32), keys,
                                token_idx, temps, top_ks, top_ps)
            return _strip(nc), tok.astype(jnp.int32)

        return prefill

    def _make_read_page(self):
        def read_page(pools, page):
            """One page's [page_size, Hkv, D] K/V slice from every pool
            (the offload side of the host tier)."""
            def walk(c):
                if isinstance(c, dict) and "pk" in c:
                    return {"pk": jax.lax.dynamic_index_in_dim(
                                c["pk"], page, 0, keepdims=False),
                            "pv": jax.lax.dynamic_index_in_dim(
                                c["pv"], page, 0, keepdims=False)}
                if isinstance(c, dict):
                    return {k: walk(v) for k, v in c.items()}
                return c
            return {k: walk(v) for k, v in pools.items()}

        return read_page

    def _make_write_page(self):
        def write_page(pools, page, payload):
            """One page's K/V slice back into every pool (the restore
            side); pools are donated, so the write is in place."""
            def walk(c, p):
                if isinstance(c, dict) and "pk" in c:
                    return {"pk": jax.lax.dynamic_update_index_in_dim(
                                c["pk"], p["pk"].astype(c["pk"].dtype),
                                page, 0),
                            "pv": jax.lax.dynamic_update_index_in_dim(
                                c["pv"], p["pv"].astype(c["pv"].dtype),
                                page, 0)}
                if isinstance(c, dict):
                    return {k: walk(v, p[k]) for k, v in c.items()}
                return c
            return {k: walk(v, payload[k]) for k, v in pools.items()}

        return write_page

    # ------------------------------------------------------------- dispatch
    def decode(self, params, net_state, pools, block, pos, tokens, keys,
               token_idx, temps, top_ks, top_ps, expected: bool = False):
        if self.detector is not None:
            self.detector.check(("decode", tokens, pos, block), {},
                                expected=expected)
        return self._decode(params, net_state, pools, block, pos, tokens,
                            keys, token_idx, temps, top_ks, top_ps)

    def prefill(self, bucket, params, net_state, pools, block, start,
                last_idx, tokens, keys, token_idx, temps, top_ks, top_ps,
                expected: bool = False):
        if self.detector is not None:
            self.detector.check((f"prefill_{bucket}", tokens, start), {},
                                expected=expected)
        return self._prefill[bucket](
            params, net_state, pools, block, start, last_idx, tokens,
            keys, token_idx, temps, top_ks, top_ps)

    def read_page(self, pools, page: int, expected: bool = False):
        """Device → host: one page's K/V slices as a numpy payload."""
        if self.detector is not None:
            self.detector.check(("read_page",), {}, expected=expected)
        return jax.device_get(self._read_page(pools, np.int32(page)))

    def write_page(self, pools, page: int, payload,
                   expected: bool = False):
        """Host → device: write a payload into page ``page``; returns
        the new pools (the old ones are donated/consumed)."""
        if self.detector is not None:
            self.detector.check(("write_page",), {}, expected=expected)
        return self._write_page(pools, np.int32(page), payload)

    def page_nbytes(self, pools) -> int:
        """Host bytes one offloaded page costs (every pool's K+V slice)
        — the unit of the prefix cache's host-tier budget."""
        total = 0
        def walk(c):
            nonlocal total
            if isinstance(c, dict) and "pk" in c:
                total += ((c["pk"].nbytes + c["pv"].nbytes)
                          // c["pk"].shape[0])
            elif isinstance(c, dict):
                for v in c.values():
                    walk(v)
        walk(pools)
        return total

    # --------------------------------------------------------------- warmup
    def warm(self) -> int:
        """AOT-compile every program on a SCRATCH pool (donation consumes
        it; the live pool is never touched) through the version's
        detector as planned compiles.  Returns the number of programs
        warmed — after this, steady-state serving compiles nothing.

        Warmup is also the memory-observability hook: the KV pool /
        params ledger is recorded here (metadata walk), and when a
        ``ShardStatsCollector`` is installed each program additionally
        gets its HLO memory + collective census (abstract lowering on
        the scratch args, BEFORE they are donated).  Cost note: the
        census ``lower().compile()`` does not share jit's dispatch
        cache, so a collector-on warmup compiles each program once more
        — the same documented one-off-per-signature price the
        ``StepProfiler`` cost-analysis seam pays (profiling.py), only
        ever while the opt-in collector is installed."""
        from deeplearning4j_tpu.observability import shardstats

        s, maxp = self.slots, self.pages_per_slot
        zeros_i = np.zeros
        pools = self.fresh_pools()
        shardstats.record_ledger(
            "generation",
            {"params": self.net.params, "net_state": self.net.net_state,
             "kv_pools": pools})
        coll = shardstats.active_collector()
        if coll is not None:
            # census at the exact warmup signatures; lower-only, so the
            # scratch pools below are still live for the real dispatches
            coll.analyze_program(
                self._decode, "generation.decode",
                (self.net.params, self.net.net_state, pools,
                 zeros_i((s, maxp), np.int32), zeros_i((s,), np.int32),
                 zeros_i((s,), np.int32), zeros_i((s, 2), np.uint32),
                 zeros_i((s,), np.int32), zeros_i((s,), np.float32),
                 zeros_i((s,), np.int32), np.ones((s,), np.float32)))
            for b in self.prefill_buckets:
                coll.analyze_program(
                    self._prefill[b], f"generation.prefill_{b}",
                    (self.net.params, self.net.net_state, pools,
                     zeros_i((1, maxp), np.int32), zeros_i((1,), np.int32),
                     np.int32(0), zeros_i((1, b), np.int32),
                     zeros_i((1, 2), np.uint32), zeros_i((1,), np.int32),
                     zeros_i((1,), np.float32), zeros_i((1,), np.int32),
                     np.ones((1,), np.float32)))
        for b in self.prefill_buckets:
            pools, _ = self.prefill(
                b, self.net.params, self.net.net_state, pools,
                zeros_i((1, maxp), np.int32), zeros_i((1,), np.int32),
                np.int32(0), zeros_i((1, b), np.int32),
                zeros_i((1, 2), np.uint32), zeros_i((1,), np.int32),
                zeros_i((1,), np.float32), zeros_i((1,), np.int32),
                np.ones((1,), np.float32), expected=True)
        pools, tok = self.decode(
            self.net.params, self.net.net_state, pools,
            zeros_i((s, maxp), np.int32), zeros_i((s,), np.int32),
            zeros_i((s,), np.int32), zeros_i((s, 2), np.uint32),
            zeros_i((s,), np.int32), zeros_i((s,), np.float32),
            zeros_i((s,), np.int32), np.ones((s,), np.float32),
            expected=True)
        payload = self.read_page(pools, 1, expected=True)
        pools = self.write_page(pools, 1, payload, expected=True)
        jax.block_until_ready(tok)
        del pools
        return len(self.prefill_buckets) + 3
