"""Persistent cross-request KV reuse: radix-tree prefix cache + host tier.

PR 13's chained-hash index only dedups prompts that are in flight
*simultaneously* — a completed request frees its pages, so the shared
system prompts / few-shot templates / re-sent chat histories that
dominate real traffic re-prefill from scratch on every request.  This
module promotes that index to a PERSISTENT radix tree over token
sequences whose nodes own refcounted KV pages that outlive the request:

- **Radix tree**: one node per ``page_size``-token chunk, children
  keyed by the exact token tuple (no hash, no collisions).  A node's KV
  content is a function of the WHOLE chain from the root (attention
  mixes every earlier position into each page), which the tree
  structure encodes for free — matching IS chain-hashing.
- **Retention**: on request completion the scheduler's existing
  ``cache.free(req.pages)`` drops the request's refs, but the tree
  holds ONE allocator ref per resident node, so prompt pages stay
  cached (LRU-ordered) instead of returning to the free list.  Decode
  tail pages are never registered and free exactly as before.
- **Admission pricing**: a hit drops the pages a request must prefill
  from ⌈prompt/page⌉ to ⌈suffix/page⌉, so more requests admit at the
  same page budget.  Refs on matched nodes are taken FIRST — before
  any eviction runs — so a mid-admission hit can never have its pages
  evicted out from under it.
- **Eviction order**: only unpinned nodes whose allocator refcount is
  exactly the tree's own (no in-flight sharer) are candidates, coldest
  ``last_use`` first, deepest first on ties (leaves before the chain
  that leads to them).  Victims spill to the host-RAM tier when the
  byte budget allows; otherwise childless victims are dropped outright
  (an interior node is never dropped while children are reachable —
  that would orphan valid KV).
- **Host tier** (serving-side twin of the training checkpointing
  device→host ``snapshot_trees``): an offloaded node's page slice is
  copied to host memory through the engine's pool transport and its
  device page freed; a later hit restores the payload into a freshly
  allocated page.  Round-trips are bit-exact (tested).
- **Pinning**: sessions ``pin()`` their conversation prefix so
  multi-turn chats never re-prefill history; pinned nodes are exempt
  from offload AND eviction.  ``unpin`` of an unknown/already-released
  pin id raises.
- **Invalidation**: cached KV is a function of the weights.  The
  engine stamps the tree with the serving version key; the decode loop
  invalidates the whole tree the first iteration it observes a
  hot-swap/rollback (and whenever the pools are reseeded).  A match
  against a node carrying a stale version tag raises
  ``StalePrefixError`` — that is a correctness bug, never a fallback.

Thread-ownership: allocator- and pool-touching methods (``admit``,
``invalidate``, payload transport) run only on the engine's single
decode thread (or before it starts).  ``pin``/``unpin``/``stats`` are
client-thread-safe: they touch only tree bookkeeping under the lock.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

from deeplearning4j_tpu.generation.paged_cache import (
    PagedKVCache, PageExhaustedError,
)


class StalePrefixError(AssertionError):
    """A radix-tree match produced a node prefilled under DIFFERENT
    weights than the serving version — a stale hit would silently serve
    tokens conditioned on a dead model, so this is an assertion, not a
    recoverable miss."""


class PrefixCacheConfig:
    """Knobs for the persistent prefix cache (``GenerationEngine``
    accepts an instance — or ``True`` for these defaults — as its
    ``prefix_cache=`` argument)."""

    def __init__(self, host_budget_bytes: int = 64 << 20):
        if host_budget_bytes < 0:
            raise ValueError(
                f"host_budget_bytes={host_budget_bytes} must be >= 0")
        self.host_budget_bytes = int(host_budget_bytes)


class _Node:
    """One ``page_size``-token chunk of some cached prompt chain."""

    __slots__ = ("chunk", "parent", "children", "page", "host", "pins",
                 "last_use", "version", "depth")

    def __init__(self, chunk: Tuple[int, ...], parent: "Optional[_Node]",
                 page: Optional[int], version: str, depth: int):
        self.chunk = chunk
        self.parent = parent
        self.children: "Dict[Tuple[int, ...], _Node]" = {}
        self.page = page          # device page id, or None when offloaded
        self.host = None          # host payload pytree when offloaded
        self.pins = 0
        self.last_use = 0
        self.version = version
        self.depth = depth


class AdmitResult:
    """What one cache-aware admission decided (scheduler stores it on
    the request so a failed prefill can unwind its created nodes)."""

    __slots__ = ("pages", "shared_len", "created", "restored_pages",
                 "offloaded_pages")

    def __init__(self, pages: List[int], shared_len: int,
                 created: List[_Node], restored_pages: int,
                 offloaded_pages: int):
        self.pages = pages
        self.shared_len = shared_len
        self.created = created
        self.restored_pages = restored_pages
        self.offloaded_pages = offloaded_pages


class PrefixCache:
    """See module docstring.  ``transport`` must expose
    ``cache_read_page(page) -> host payload`` and
    ``cache_write_page(page, payload)`` over the live pools (the engine
    wires its jitted page transport; unit tests pass a numpy one);
    without a transport (or a known ``page_bytes``) the host tier is
    disabled and evictions drop pages outright."""

    def __init__(self, cache: PagedKVCache, *,
                 host_budget_bytes: int = 64 << 20,
                 transport=None, page_bytes: Optional[int] = None,
                 metrics=None):
        self.cache = cache
        self.page_size = cache.page_size
        self.host_budget_bytes = int(host_budget_bytes)
        self.transport = transport
        self.page_bytes = page_bytes
        self.metrics = metrics
        self.version: str = ""
        self._lock = threading.RLock()
        self._root = _Node((), None, None, "", 0)
        self._all: "set[_Node]" = set()
        self._clock = 0
        self._pins: Dict[int, List[_Node]] = {}
        self._next_pin = 0
        # counters mirrored into stats()/metrics
        self.hits = 0
        self.misses = 0
        self.offload_total = 0
        self.restore_total = 0
        self.host_bytes = 0
        self.evictions: Dict[str, int] = {}

    # -------------------------------------------------------------- wiring
    def attach(self, transport, page_bytes: int) -> None:
        """Engine hookup: the pool transport and the host bytes one page
        costs (sum of per-layer K+V slice nbytes) for budget math."""
        with self._lock:
            self.transport = transport
            self.page_bytes = int(page_bytes)

    def set_version(self, tag: str) -> None:
        with self._lock:
            self.version = str(tag)

    # ------------------------------------------------------------ admission
    def admit(self, prompt: Sequence[int],
              max_new_tokens: int) -> AdmitResult:
        """Cache-aware admission: one transaction that matches the
        longest cached page-aligned prefix, refs it, evicts/offloads
        cold nodes to make room, restores offloaded hits, allocates the
        fresh remainder, and registers this prompt's new full pages as
        tree nodes.  Raises ``PageExhaustedError`` — with every taken
        ref unwound — when unpinned refcount-free nodes cannot yield
        enough room (the scheduler keeps the request queued)."""
        with self._lock:
            prompt = [int(t) for t in prompt]
            occupancy = len(prompt) + max(1, int(max_new_tokens)) - 1
            total = self.cache.pages_needed(occupancy)
            if total > self.cache.pages_per_slot:
                raise ValueError(
                    f"request needs {total} pages "
                    f"({len(prompt)} prompt + {max_new_tokens} new tokens) "
                    f"but the block table holds {self.cache.pages_per_slot} "
                    f"(max_context={self.cache.max_context})")
            # longest cached page-aligned prefix, capped so at least ONE
            # prompt token is left to prefill (its logits seed sampling)
            matched = self._match(prompt, (len(prompt) - 1) // self.page_size)
            # refs FIRST: a matched resident page must be un-evictable
            # before any room-making below can consider it.  A ref only
            # exists for RESIDENT matches — a host-tier match has no
            # page yet — so every matched node also takes a temporary
            # admission pin: pins exclude a node from _make_room's
            # victim set AND from _drop_host_leaf, which could otherwise
            # drop a cold matched host node (detaching it from the tree
            # and nulling the payload the restore loop is about to
            # write back).
            for n in matched:
                if n.page is not None:
                    self.cache.ref(n.page)
                n.pins += 1
            to_restore = [n for n in matched if n.page is None]
            fresh_count = total - len(matched)
            offload_before = self.offload_total
            try:
                self._make_room(fresh_count + len(to_restore))
            except PageExhaustedError:
                for n in matched:       # unwind: request refs and the
                    if n.page is not None:   # admission pins — the
                        self.cache.free([n.page])  # tree's ref stays
                    n.pins -= 1
                raise
            # restore offloaded hits into fresh device pages (payload
            # written through the transport NOW — admit runs on the
            # decode thread, which owns the pools)
            for n in to_restore:
                page = self.cache.alloc(1)[0]   # tree's ref
                self.cache.ref(page)            # this request's ref
                self.transport.cache_write_page(page, n.host)
                n.page = page
                n.host = None
                self.host_bytes -= self.page_bytes
                self.restore_total += 1
                if self.metrics is not None:
                    self.metrics.prefix_cache_restores.inc()
            for n in matched:   # restore done: every matched node is
                n.pins -= 1     # resident + request-ref'd, so the
                                # admission pins have done their job
            fresh = self.cache.alloc(fresh_count)
            pages = [n.page for n in matched] + fresh
            # register this request's freshly prefilled full prompt
            # pages as new tree nodes (tree takes its own ref on each)
            created: List[_Node] = []
            parent = matched[-1] if matched else self._root
            for i in range(len(matched), len(prompt) // self.page_size):
                chunk = tuple(prompt[i * self.page_size:
                                     (i + 1) * self.page_size])
                existing = parent.children.get(chunk)
                if existing is not None:
                    # a node deeper than the match cap (the last prompt
                    # token always prefills, so a fully-paged prompt can
                    # out-run its own match): keep the cached node — its
                    # KV is the same deterministic function of the chain
                    # — and leave this request's fresh page private
                    parent = existing
                    continue
                node = _Node(chunk, parent, pages[i], self.version,
                             parent.depth + 1)
                self.cache.ref(pages[i])
                parent.children[chunk] = node
                self._all.add(node)
                created.append(node)
                parent = node
            self._clock += 1
            for n in matched + created:
                n.last_use = self._clock
            self.cache.shared_pages += len(matched)
            self.cache.fresh_pages += fresh_count
            if matched:
                self.hits += 1
            else:
                self.misses += 1
            if self.metrics is not None:
                (self.metrics.prefix_cache_hits if matched
                 else self.metrics.prefix_cache_misses).inc()
            return AdmitResult(pages, len(matched) * self.page_size,
                               created, len(to_restore),
                               self.offload_total - offload_before)

    def _match(self, prompt: List[int], max_pages: int) -> List[_Node]:
        # private helpers re-take the RLock their public callers already
        # hold: free (reentrant) and keeps the lock discipline checkable
        with self._lock:
            node, matched = self._root, []
            for i in range(max_pages):
                child = node.children.get(
                    tuple(prompt[i * self.page_size:
                                 (i + 1) * self.page_size]))
                if child is None:
                    break
                if child.version != self.version:
                    raise StalePrefixError(
                        f"radix node prefilled under version "
                        f"{child.version!r} matched while serving "
                        f"{self.version!r} — invalidation on swap failed")
                matched.append(child)
                node = child
            return matched

    # ------------------------------------------------------------- eviction
    def _tree_only(self, node: _Node) -> bool:
        """True when the tree's own ref is the page's ONLY ref (no
        in-flight request shares it)."""
        return (node.page is not None
                and self.cache.refcount(node.page) == 1)

    def _make_room(self, needed: int) -> None:
        """Free device pages until ``needed`` fit, spilling victims to
        the host tier when the budget allows, dropping childless ones
        otherwise.  Never touches pinned nodes or pages an in-flight
        request still references."""
        with self._lock:
            while self.cache.free_pages < needed:
                victims = [n for n in self._all
                           if self._tree_only(n) and n.pins == 0]
                if not victims:
                    raise PageExhaustedError(
                        f"need {needed} pages, {self.cache.free_pages} "
                        f"free and no unpinned refcount-free cache node "
                        f"to evict")
                victim = min(victims,
                             key=lambda n: (n.last_use, -n.depth))
                if self._host_has_room():
                    self._offload(victim)
                else:
                    # dropping an interior node would orphan reachable
                    # descendants; walk down to the coldest childless one
                    droppable = [n for n in victims if not n.children]
                    if not droppable:
                        # resident interiors whose children are host-only:
                        # clear cold host leaves first, then loop
                        if not self._drop_host_leaf("capacity"):
                            raise PageExhaustedError(
                                f"need {needed} pages, "
                                f"{self.cache.free_pages} free and every "
                                "droppable node is pinned or in flight")
                        continue
                    self._drop(min(droppable,
                                   key=lambda n: (n.last_use, -n.depth)),
                               "capacity")

    def _host_has_room(self) -> bool:
        with self._lock:
            if self.transport is None or not self.page_bytes:
                return False
            while (self.host_bytes + self.page_bytes
                   > self.host_budget_bytes):
                if not self._drop_host_leaf("host_capacity"):
                    return False
            return True

    def _offload(self, node: _Node) -> None:
        """Device → host: copy the page slice out through the transport,
        free the device page (the tree's ref), keep the node."""
        with self._lock:
            node.host = self.transport.cache_read_page(node.page)
            self.cache.free([node.page])
            node.page = None
            self.host_bytes += self.page_bytes
            self.offload_total += 1
            if self.metrics is not None:
                self.metrics.prefix_cache_offloads.inc()

    def _drop_host_leaf(self, reason: str) -> bool:
        """Evict the coldest childless host-tier node; returns False
        when none exists (every host node is pinned or interior)."""
        with self._lock:
            leaves = [n for n in self._all
                      if n.host is not None and n.pins == 0
                      and not n.children]
            if not leaves:
                return False
            self._drop(min(leaves, key=lambda n: n.last_use), reason)
            return True

    def _drop(self, node: _Node, reason: str) -> None:
        """Remove one childless node entirely (device page freed or host
        bytes returned)."""
        with self._lock:
            if node.children:
                raise AssertionError(
                    "dropping an interior radix node would orphan its "
                    "children")
            if node.page is not None:
                self.cache.free([node.page])
            if node.host is not None:
                self.host_bytes -= self.page_bytes or 0
            node.parent.children.pop(node.chunk, None)
            self._all.discard(node)
            self.evictions[reason] = self.evictions.get(reason, 0) + 1
            if self.metrics is not None:
                self.metrics.prefix_cache_evictions.inc(reason=reason)

    def forget(self, result: AdmitResult) -> None:
        """Unwind the nodes one failed admission created: its prefill
        never wrote them, so a later match would serve garbage.  Runs
        BEFORE the scheduler frees the request's pages (the tree refs
        dropped here are the nodes' own)."""
        with self._lock:
            for node in reversed(result.created):
                if node.chunk in node.parent.children:
                    self._drop(node, "abort")

    # ------------------------------------------------------------- pinning
    def pin(self, prompt: Sequence[int]) -> int:
        """Pin every currently-cached page of ``prompt``'s prefix
        against offload and eviction; returns a pin id for ``unpin``.
        Multi-turn sessions pin their history after each turn so the
        next turn's prefill only ever covers the new tokens."""
        with self._lock:
            prompt = [int(t) for t in prompt]
            nodes = self._match(prompt, len(prompt) // self.page_size)
            for n in nodes:
                n.pins += 1
            self._clock += 1
            for n in nodes:
                n.last_use = self._clock
            pin_id = self._next_pin
            self._next_pin += 1
            self._pins[pin_id] = nodes
            return pin_id

    def unpin(self, pin_id: int) -> None:
        """Release one pin.  Unknown or already-released ids raise
        ``KeyError`` — a double unpin means the session's refcounting
        is broken and silently ignoring it would mask real leaks."""
        with self._lock:
            nodes = self._pins.pop(pin_id)   # KeyError on double unpin
            for n in nodes:
                if n.pins < 1:
                    raise AssertionError(
                        f"pin underflow on node depth={n.depth}")
                n.pins -= 1

    def pinned_pages(self) -> int:
        with self._lock:
            return sum(1 for n in self._all if n.pins > 0)

    # -------------------------------------------------------- invalidation
    def invalidate(self, reason: str) -> int:
        """Drop the WHOLE tree (cached KV is a function of the weights
        and of the live pools): every tree-held device ref is freed —
        pages an in-flight request still shares survive under the
        request's own refs — the host tier is emptied, and existing
        pins go stale (their one legal ``unpin`` still works).
        Returns the number of nodes invalidated."""
        with self._lock:
            count = len(self._all)
            for node in self._all:
                if node.page is not None:
                    self.cache.free([node.page])
                    node.page = None
                node.host = None
            self._all.clear()
            self._root.children.clear()
            self.host_bytes = 0
            for pid in self._pins:
                self._pins[pid] = []
            if count:
                self.evictions[reason] = (self.evictions.get(reason, 0)
                                          + count)
                if self.metrics is not None:
                    self.metrics.prefix_cache_evictions.inc(count,
                                                            reason=reason)
            return count

    # ---------------------------------------------------------------- stats
    def resident_pages(self) -> int:
        with self._lock:
            return sum(1 for n in self._all if n.page is not None)

    def host_pages(self) -> int:
        with self._lock:
            return sum(1 for n in self._all if n.host is not None)

    def stats(self) -> dict:
        with self._lock:
            total = self.hits + self.misses
            return {
                "version": self.version,
                "nodes": len(self._all),
                "resident_pages": self.resident_pages(),
                "host_pages": self.host_pages(),
                "host_tier_bytes": self.host_bytes,
                "host_budget_bytes": self.host_budget_bytes,
                "pinned_pages": self.pinned_pages(),
                "pins_open": sum(1 for v in self._pins.values() if v),
                "hits": self.hits,
                "misses": self.misses,
                "hit_rate": round(self.hits / total, 4) if total else 0.0,
                "offload_total": self.offload_total,
                "restore_total": self.restore_total,
                "evictions_total": dict(self.evictions),
            }
