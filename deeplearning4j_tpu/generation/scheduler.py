"""Iteration-level decode scheduling: requests join/leave a RUNNING batch.

The PR-2 batcher composes whole requests into one forward pass; that is
the wrong granularity for autoregressive decode, where a 500-token
completion would pin its batch slot for the whole tail while finished
requests' lanes idle.  ``DecodeScheduler`` schedules at ITERATION
granularity (Orca/vLLM): every decode step serves whatever requests are
active RIGHT NOW — new arrivals prefill into free slots between steps,
finished/cancelled/expired requests free their slot and pages
mid-flight, and the batch never drains to restart.

Admission is the only capacity gate: a request is admitted when a slot
is free AND its full page budget (prompt + max-new-tokens, minus any
shared prefix) fits the pool, so decode can never stall mid-flight on
pages.  The bounded pending queue sheds with the serving-stack errors
(429 ``QueueFullError`` / 503 ``ShuttingDownError`` / 504
``DeadlineExceededError``) instead of ever hanging a caller.

Threading: ``submit``/``cancel`` run on client threads and only touch
the pending deque + per-request flags (lock-guarded); everything else
(slots, block tables, the page allocator) is owned by the engine's
single decode thread.
"""

from __future__ import annotations

import queue
import threading
import time
from collections import deque
from typing import List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.observability.tracing import new_trace_id
from deeplearning4j_tpu.serving.admission import (
    AdmissionController, DeadlineExceededError, ShuttingDownError,
)
from deeplearning4j_tpu.generation.paged_cache import (
    PagedKVCache, PageExhaustedError,
)

_DONE = object()   # stream sentinel


class GenerationRequest:
    """One generation request: client-facing handle + scheduler state.

    Clients read ``stream()`` / ``tokens()`` / ``cancel()``; everything
    else belongs to the scheduler.  Tokens are delivered per decode
    step, so ``stream()`` yields them as they are generated."""

    def __init__(self, prompt: Sequence[int], max_new_tokens: int, *,
                 temperature: float = 0.0, top_k: Optional[int] = None,
                 top_p: Optional[float] = None, seed: int = 0,
                 deadline_s: float = 60.0, stop_token: Optional[int] = None,
                 trace_id: Optional[str] = None):
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens={max_new_tokens} must be >= 1")
        self.prompt = [int(t) for t in np.asarray(prompt).reshape(-1)]
        if not self.prompt:
            raise ValueError("empty prompt")
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.top_k = int(top_k) if top_k is not None else 0
        self.top_p = float(top_p) if top_p is not None else 1.0
        if self.top_k < 0:
            raise ValueError(f"top_k={top_k} must be >= 1 (or None)")
        self.seed = int(seed)
        self.deadline = time.monotonic() + float(deadline_s)
        self.stop_token = None if stop_token is None else int(stop_token)
        self.trace_id = trace_id or new_trace_id()
        self.submitted = time.perf_counter()
        self.ttft_s: Optional[float] = None
        self.itl_s: List[float] = []    # gaps between delivered tokens
        self._last_token_t: Optional[float] = None
        self.slo_ok: Optional[bool] = None   # set by the engine's SLOTracker
        self.finish_reason: Optional[str] = None   # length|stop|cancelled…
        self.tokens: List[int] = []
        self.error: Optional[Exception] = None
        self.done = threading.Event()
        self.cancelled = False          # client flag, polled per step
        self._stream: "queue.Queue" = queue.Queue()
        # scheduler-owned (decode thread only)
        self.slot: Optional[int] = None
        self.pages: List[int] = []
        self.shared_len = 0
        self.cache_admit = None   # AdmitResult when retention is active

    # ----------------------------------------------------------- client API
    def cancel(self) -> None:
        """Ask the scheduler to drop this request at the next step
        boundary (its pages free mid-flight; already-streamed tokens
        stand)."""
        self.cancelled = True

    def stream(self, timeout: Optional[float] = None):
        """Yield token ids as they are generated; raises the request's
        terminal error (shed/deadline/model failure), if any, after the
        last delivered token."""
        while True:
            item = self._stream.get(timeout=timeout)
            if item is _DONE:
                if self.error is not None:
                    raise self.error
                return
            yield item

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """Block until the request finishes; returns all generated
        tokens or raises the terminal error."""
        if not self.done.wait(timeout):
            raise TimeoutError(
                f"generation still running [trace {self.trace_id}]")
        if self.error is not None:
            raise self.error
        return list(self.tokens)

    # -------------------------------------------------------- delivery side
    def _deliver(self, token: int) -> None:
        now = time.perf_counter()
        if self.ttft_s is None:
            self.ttft_s = now - self.submitted
        else:
            self.itl_s.append(now - self._last_token_t)
        self._last_token_t = now
        self.tokens.append(int(token))
        self._stream.put(int(token))

    def itl_p50_ms(self) -> Optional[float]:
        """Median inter-token gap in ms (None before the second token) —
        the per-request SLO evidence the access log carries."""
        if not self.itl_s:
            return None
        vs = sorted(self.itl_s)
        mid = len(vs) // 2
        p50 = vs[mid] if len(vs) % 2 else 0.5 * (vs[mid - 1] + vs[mid])
        return round(p50 * 1e3, 3)

    def _finish(self, reason: str, error: Optional[Exception] = None) -> None:
        self.finish_reason = reason
        self.error = error
        self._release_waiters()

    def _release_waiters(self) -> None:
        self._stream.put(_DONE)
        self.done.set()

    def as_dict(self) -> dict:
        return {"trace_id": self.trace_id, "prompt_tokens": len(self.prompt),
                "generated": len(self.tokens),
                "max_new_tokens": self.max_new_tokens,
                "finish_reason": self.finish_reason,
                "ttft_ms": (round(self.ttft_s * 1e3, 3)
                            if self.ttft_s is not None else None),
                "itl_p50_ms": self.itl_p50_ms(),
                "slo_ok": self.slo_ok}


class _Slot:
    """Decode-thread-side state of one running request."""

    __slots__ = ("req", "pos", "generated")

    def __init__(self, req: GenerationRequest, pos: int):
        self.req = req
        self.pos = pos            # stream position the NEXT write lands at
        self.generated = 1        # prefill already sampled token 0


class DecodeScheduler:
    """Slots + pending queue + page allocator (see module docstring)."""

    def __init__(self, cache: PagedKVCache, *, slots: int,
                 max_queue: int = 64, default_deadline_s: float = 60.0,
                 metrics=None):
        if slots < 1:
            raise ValueError(f"slots={slots} must be >= 1")
        self.cache = cache
        self.num_slots = int(slots)
        self.admission = AdmissionController(
            max_queue=max_queue, default_deadline_s=default_deadline_s,
            metrics=metrics)
        self.metrics = metrics
        # terminal hook (engine accounting): called once per request on
        # ANY terminal path, BEFORE the request's done event is set — so
        # per-request verdicts the hook computes (slo_ok) are visible the
        # moment result()/stream() return
        self.on_finish = None
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._pending: "deque[GenerationRequest]" = deque()
        self._stopping = False
        self.slots: List[Optional[_Slot]] = [None] * self.num_slots
        maxp = cache.pages_per_slot
        # the decode step's host-side mirror arrays, updated in place on
        # admit/retire and handed to the jitted step every iteration
        self.block = np.zeros((self.num_slots, maxp), np.int32)
        self.pos = np.zeros(self.num_slots, np.int32)
        self.last_tok = np.zeros(self.num_slots, np.int32)
        self.keys = np.zeros((self.num_slots, 2), np.uint32)
        self.tok_idx = np.zeros(self.num_slots, np.int32)
        self.temps = np.zeros(self.num_slots, np.float32)
        self.top_ks = np.zeros(self.num_slots, np.int32)
        self.top_ps = np.ones(self.num_slots, np.float32)

    # ----------------------------------------------------------- client side
    def submit(self, req: GenerationRequest) -> GenerationRequest:
        """Admission-checked enqueue (client threads).  A request whose
        worst-case page budget can NEVER fit the pool fails immediately
        (ValueError — resubmitting cannot help); a full pending queue
        sheds 429; shutdown sheds 503."""
        worst = self.cache.pages_needed(
            len(req.prompt) + req.max_new_tokens - 1)
        if worst > self.cache.pages_per_slot:
            raise ValueError(
                f"request needs {worst} pages but a slot holds "
                f"{self.cache.pages_per_slot} "
                f"(max_context={self.cache.max_context})")
        with self._wake:
            self.admission.check_admit(len(self._pending), self._stopping,
                                       trace_id=req.trace_id)
            self._pending.append(req)
            self._wake.notify_all()
        return req

    def wait_for_work(self, timeout: float) -> None:
        """Decode-thread idle wait: returns early when a request arrives
        or stop is requested."""
        with self._wake:
            if not self._pending and not self._stopping:
                self._wake.wait(timeout)

    def reopen(self) -> None:
        """Re-arm admission after a shutdown (engine restart)."""
        with self._wake:
            self._stopping = False

    def begin_shutdown(self, drain_pending: bool) -> None:
        """Stop admitting.  Without ``drain_pending`` every queued
        request fails 503 now; active requests are the engine's to
        finish or fail."""
        with self._wake:
            self._stopping = True
            pending = list(self._pending) if not drain_pending else []
            if not drain_pending:
                self._pending.clear()
            self._wake.notify_all()
        for req in pending:
            err = self.admission.shed(ShuttingDownError,
                                      "engine is shutting down",
                                      trace_id=req.trace_id)
            self._terminate(req, "shutdown", err)

    def _terminate(self, req: GenerationRequest, reason: str,
                   error: Optional[Exception] = None) -> None:
        if req.done.is_set() or req.finish_reason is not None:
            return   # already terminal (stop() races the loop's own end)
        req.finish_reason = reason
        req.error = error
        try:
            # accounting BEFORE the waiters wake: the hook stamps the
            # request (slo_ok) and a client reading result() right after
            # done.set() must see the stamp, not race it
            if self.on_finish is not None:
                self.on_finish(req)
        finally:
            req._release_waiters()

    @property
    def queued(self) -> int:
        with self._lock:
            return len(self._pending)

    # ----------------------------------------------------- decode-thread side
    def active_slots(self) -> List[int]:
        return [i for i, s in enumerate(self.slots) if s is not None]

    @property
    def has_work(self) -> bool:
        with self._lock:
            pending = bool(self._pending)
        return pending or any(s is not None for s in self.slots)

    def purge_pending(self, now: Optional[float] = None) -> List[GenerationRequest]:
        """Fail queued requests whose deadline passed without ever
        running (504, no forward pass spent) — the queue-side purge the
        PR-2 batcher does for predict."""
        now = time.monotonic() if now is None else now
        out: List[GenerationRequest] = []
        with self._lock:
            keep: "deque[GenerationRequest]" = deque()
            for req in self._pending:
                if req.cancelled or now > req.deadline:
                    out.append(req)
                else:
                    keep.append(req)
            self._pending = keep
        for req in out:
            if req.cancelled:
                self._terminate(req, "cancelled")
            else:
                err = self.admission.shed(
                    DeadlineExceededError,
                    "deadline expired while queued for a decode slot",
                    trace_id=req.trace_id)
                self._terminate(req, "deadline", err)
        return out

    def next_admittable(self) -> Optional[GenerationRequest]:
        """Pop the oldest pending request IF a slot is free and its page
        budget fits (allocates pages + a slot; the caller prefills it
        immediately).  FIFO: a head request that doesn't fit blocks
        later ones — admission order is completion-order fairness, not
        best-fit packing.

        With a retention policy installed, admission is cache-aware: the
        radix tree prices the request at ⌈suffix/page⌉ instead of
        ⌈prompt/page⌉ on a hit, refs the matched pages before anything
        can evict them, and may evict/offload cold unpinned tree nodes
        to make room — ``PageExhaustedError`` then means even eviction
        could not free enough."""
        free = next((i for i, s in enumerate(self.slots) if s is None), None)
        if free is None:
            return None
        with self._lock:
            if not self._pending:
                return None
            req = self._pending[0]
            admit_result = None
            try:
                # never-fits requests were rejected at submit(), so the
                # only failure here is transient pool pressure
                if self.cache.retention is not None:
                    admit_result = self.cache.retention.admit(
                        req.prompt, req.max_new_tokens)
                    pages = admit_result.pages
                    shared_len = admit_result.shared_len
                else:
                    pages, shared_len = self.cache.admit(req.prompt,
                                                         req.max_new_tokens)
            except PageExhaustedError:
                return None     # keep queued; pages free as slots retire
            self._pending.popleft()
        req.slot = free
        req.pages = pages
        req.shared_len = shared_len
        req.cache_admit = admit_result
        return req

    def fail_admitted(self, req: GenerationRequest,
                      error: Exception) -> None:
        """Terminal path for a request that was admitted (pages + slot
        reserved) but whose PREFILL failed before ``install``: free the
        pages (which also drops any prefix-index entries registered for
        its never-written pages) and release the waiters — without this
        the request is invisible to ``evict_all`` and would hang its
        clients forever while leaking its pages."""
        if req.cache_admit is not None:
            # radix nodes this admission created were never prefilled;
            # drop them (and the tree's refs) before the request's own
            # refs go, or a later match would serve unwritten pages
            self.cache.retention.forget(req.cache_admit)
            req.cache_admit = None
        self.cache.free(req.pages)
        req.pages = []
        req.slot = None
        self._terminate(req, "error", error)
        if self.metrics is not None:
            self.metrics.evictions.inc(reason="error")

    def install(self, req: GenerationRequest, first_token: int,
                base_key: np.ndarray) -> None:
        """Bind an admitted+prefilled request to its slot: mirror arrays
        pick it up from the next decode step on."""
        i = req.slot
        self.slots[i] = _Slot(req, pos=len(req.prompt))
        self.block[i] = self.cache.block_row(req.pages)
        self.pos[i] = len(req.prompt)
        self.last_tok[i] = int(first_token)
        self.keys[i] = base_key
        self.tok_idx[i] = 1
        self.temps[i] = req.temperature
        self.top_ks[i] = req.top_k
        self.top_ps[i] = req.top_p
        req._deliver(first_token)
        self._maybe_finish(i, int(first_token))

    def after_step(self, sampled: np.ndarray) -> int:
        """Deliver one decode step's tokens and advance/retire slots;
        returns the number of tokens delivered."""
        delivered = 0
        now = time.monotonic()
        for i in self.active_slots():
            slot = self.slots[i]
            req = slot.req
            tok = int(sampled[i])
            slot.pos += 1
            self.pos[i] = slot.pos
            self.last_tok[i] = tok
            self.tok_idx[i] += 1
            slot.generated += 1
            req._deliver(tok)
            if self.metrics is not None and req.itl_s:
                self.metrics.inter_token.observe(req.itl_s[-1])
            delivered += 1
            if not self._maybe_finish(i, tok) and (
                    req.cancelled or now > req.deadline):
                self._evict(i, "cancelled" if req.cancelled else "deadline")
        return delivered

    def _maybe_finish(self, i: int, tok: int) -> bool:
        slot = self.slots[i] if self.slots[i] is not None else None
        if slot is None:   # install() path before the slot exists
            return False
        req = slot.req
        if req.stop_token is not None and tok == req.stop_token:
            self._retire(i, "stop")
            return True
        if slot.generated >= req.max_new_tokens:
            self._retire(i, "length")
            return True
        return False

    def _retire(self, i: int, reason: str) -> None:
        slot = self.slots[i]
        self._release(i)
        self._terminate(slot.req, reason)

    def _evict(self, i: int, reason: str,
               error: Optional[Exception] = None) -> None:
        """Mid-flight removal (deadline/cancel/shutdown/error): pages
        free NOW, the stream ends with the matching error (except
        cancel, which is a clean client-requested end)."""
        slot = self.slots[i]
        req = slot.req
        self._release(i)
        if reason == "deadline":
            err = self.admission.shed(
                DeadlineExceededError,
                f"deadline expired after {len(req.tokens)} tokens",
                trace_id=req.trace_id)
        elif reason == "shutdown":
            err = self.admission.shed(ShuttingDownError,
                                      "engine stopped mid-generation",
                                      trace_id=req.trace_id)
        elif reason == "error":
            err = error if error is not None else RuntimeError(
                f"decode step failed [trace {req.trace_id}]")
        else:
            err = None
        self._terminate(req, reason, err)
        if self.metrics is not None:
            self.metrics.evictions.inc(reason=reason)

    def _release(self, i: int) -> None:
        slot = self.slots[i]
        self.cache.free(slot.req.pages)
        self.slots[i] = None
        # park the lane on the trash page with greedy sampling
        self.block[i] = self.cache.block_row([])
        self.pos[i] = 0
        self.last_tok[i] = 0
        self.keys[i] = 0
        self.tok_idx[i] = 0
        self.temps[i] = 0.0
        self.top_ks[i] = 0
        self.top_ps[i] = 1.0

    def evict_all(self, reason: str,
                  error: Optional[Exception] = None) -> None:
        for i in self.active_slots():
            self._evict(i, reason, error)

    def as_dict(self) -> dict:
        return {"slots": self.num_slots,
                "active": len(self.active_slots()),
                "queued": self.queued,
                "cache": self.cache.as_dict(),
                "requests": [s.req.as_dict()
                             for s in self.slots if s is not None]}
