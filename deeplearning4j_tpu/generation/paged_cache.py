"""Paged KV cache: fixed-size pages + block tables + prefix sharing.

A contiguous per-request KV cache (``SelfAttentionLayer.init_cache``)
couples cache memory to ``max_cache`` per stream and couples the XLA
shape set to the batch composition — both fatal for continuous batching,
where requests of wildly different lengths join and leave a running
decode batch every step.  The paged design (vLLM's PagedAttention)
decouples them:

- **Device side** (``pools``): per attention layer, K/V pools of
  ``num_pages`` pages of ``page_size`` positions each
  (``init_paged_cache``).  Pool shapes are the only shapes XLA ever
  sees — slot count, page count, and page size close the decode shape
  set, so steady-state serving compiles exactly nothing.
- **Host side** (this class): a page allocator with per-page refcounts,
  int32 block tables mapping each slot's logical page index to a pool
  page, and a chained-hash prefix index so identical prompt prefixes
  map to the SAME read-only pages (refcounted — freed only when the
  last sharer leaves).

Page 0 is reserved as the TRASH page: unallocated block-table entries
point at it, so bucket-padding positions and idle decode slots scatter
their garbage somewhere harmless that no causal mask ever lets a real
query read.

Thread-ownership: all mutating methods are called from the engine's
single decode thread; the class itself takes no locks.
"""

from __future__ import annotations

import hashlib
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

TRASH_PAGE = 0


class PageExhaustedError(RuntimeError):
    """Not enough free pages for an allocation (the scheduler keeps the
    request queued — or sheds it — instead of partially admitting)."""


def _chain(parent: Optional[bytes], tokens: Sequence[int]) -> bytes:
    """Chained prefix key: a page's KV content is a function of the WHOLE
    prefix up to and including it (attention mixes every earlier
    position into each hidden state), so the share key must hash the
    chain, never the page's tokens alone."""
    h = hashlib.sha256()
    if parent is not None:
        h.update(parent)
    h.update(np.asarray(tokens, np.int64).tobytes())
    return h.digest()


class PagedKVCache:
    """Host-side allocator over a fixed pool of KV pages.

    ``num_pages`` counts the usable pool INCLUDING the reserved trash
    page; ``pages_per_slot`` is the block-table width (the per-request
    context ceiling is ``pages_per_slot * page_size``)."""

    def __init__(self, num_pages: int, page_size: int, pages_per_slot: int):
        if num_pages < 2:
            raise ValueError(f"num_pages={num_pages} must be >= 2 "
                             "(page 0 is the reserved trash page)")
        if page_size < 1 or pages_per_slot < 1:
            raise ValueError("page_size and pages_per_slot must be >= 1")
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self.pages_per_slot = int(pages_per_slot)
        self._free: List[int] = list(range(1, self.num_pages))
        self._refs = np.zeros(self.num_pages, np.int64)
        self._refs[TRASH_PAGE] = 1   # never allocatable
        # chained prefix hash -> page id, and the reverse for cleanup
        self._prefix: Dict[bytes, int] = {}
        self._page_key: Dict[int, bytes] = {}
        # counters the engine mirrors into metrics
        self.shared_pages = 0
        self.fresh_pages = 0
        # pluggable retention policy (generation.prefix_cache.PrefixCache):
        # when set, admission routes through its radix tree and completed
        # requests' prompt pages stay cached under the tree's own refs
        # instead of returning to the free list.  None (the default)
        # keeps the legacy free-on-release behavior bit-identical.
        self.retention = None

    # ------------------------------------------------------------ capacity
    @property
    def max_context(self) -> int:
        return self.pages_per_slot * self.page_size

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def used_pages(self) -> int:
        return (self.num_pages - 1) - len(self._free)

    def utilization(self) -> float:
        usable = self.num_pages - 1
        return (self.used_pages / usable) if usable else 0.0

    def pages_needed(self, occupancy: int) -> int:
        """Pages covering ``occupancy`` written positions."""
        return -(-max(0, int(occupancy)) // self.page_size)

    # ----------------------------------------------------------- allocation
    def admit(self, prompt: Sequence[int],
              max_new_tokens: int) -> Tuple[List[int], int]:
        """Allocate the FULL page budget for one request up front and
        return ``(pages, shared_len)``.

        ``pages`` is the request's block-table prefix (logical order);
        the first ``shared_len // page_size`` entries are refcounted
        shares of pages another in-flight request already prefilled with
        the identical chained prompt prefix — the new request's prefill
        only runs on ``prompt[shared_len:]``.  Everything past the
        prompt is reserved now (occupancy ``len(prompt) + max_new - 1``;
        the final sampled token is never fed back), so decode can never
        hit mid-flight page exhaustion: admission is the only gate.
        Raises ``PageExhaustedError`` without allocating anything when
        the pool cannot cover the non-shared remainder."""
        prompt = [int(t) for t in prompt]
        occupancy = len(prompt) + max(1, int(max_new_tokens)) - 1
        total = self.pages_needed(occupancy)
        if total > self.pages_per_slot:
            raise ValueError(
                f"request needs {total} pages "
                f"({len(prompt)} prompt + {max_new_tokens} new tokens) but "
                f"the block table holds {self.pages_per_slot} "
                f"(max_context={self.max_context})")
        # longest page-aligned shared prefix, capped so at least ONE
        # prompt token is left to prefill (the last token's logits seed
        # the first sample and are not cached with the pages)
        shared: List[int] = []
        key: Optional[bytes] = None
        max_share = min(len(self._full_prompt_pages(prompt)),
                        (len(prompt) - 1) // self.page_size)
        for i in range(max_share):
            key = _chain(key, prompt[i * self.page_size:
                                     (i + 1) * self.page_size])
            page = self._prefix.get(key)
            if page is None:
                break
            shared.append(page)
        fresh_count = total - len(shared)
        if fresh_count > len(self._free):
            raise PageExhaustedError(
                f"need {fresh_count} pages, {len(self._free)} free "
                f"(pool {self.num_pages - 1})")
        for p in shared:
            self._refs[p] += 1
        fresh = [self._free.pop() for _ in range(fresh_count)]
        for p in fresh:
            self._refs[p] = 1
        self.shared_pages += len(shared)
        self.fresh_pages += fresh_count
        pages = shared + fresh
        # register THIS request's freshly prefilled full prompt pages so
        # later identical prompts can share them
        chain_key: Optional[bytes] = None
        for i in self._full_prompt_pages(prompt):
            chain_key = _chain(chain_key,
                               prompt[i * self.page_size:
                                      (i + 1) * self.page_size])
            if i < len(shared):
                continue   # already indexed by its first owner
            if chain_key not in self._prefix:
                self._prefix[chain_key] = pages[i]
                self._page_key[pages[i]] = chain_key
        return pages, len(shared) * self.page_size

    def _full_prompt_pages(self, prompt: Sequence[int]) -> range:
        return range(len(prompt) // self.page_size)

    def alloc(self, count: int) -> List[int]:
        """Raw allocation of ``count`` pages at refcount 1 (retention
        policies use this for restore targets; ``admit`` stays the
        request-shaped entry point)."""
        if count > len(self._free):
            raise PageExhaustedError(
                f"need {count} pages, {len(self._free)} free "
                f"(pool {self.num_pages - 1})")
        pages = [self._free.pop() for _ in range(count)]
        for p in pages:
            self._refs[p] = 1
        return pages

    def ref(self, page: int) -> None:
        """Take one additional reference on an already-allocated page."""
        if page == TRASH_PAGE or self._refs[page] < 1:
            raise AssertionError(
                f"ref on unallocated page {page} (refs={self._refs[page]})")
        self._refs[page] += 1

    def free(self, pages: Sequence[int]) -> None:
        """Drop one request's references; pages return to the free list
        (and leave the prefix index) when their last sharer leaves."""
        for p in pages:
            if p == TRASH_PAGE:
                continue
            self._refs[p] -= 1
            if self._refs[p] < 0:
                raise AssertionError(f"double free of page {p}")
            if self._refs[p] == 0:
                key = self._page_key.pop(p, None)
                if key is not None:
                    self._prefix.pop(key, None)
                self._free.append(p)

    def refcount(self, page: int) -> int:
        return int(self._refs[page])

    def block_row(self, pages: Sequence[int]) -> np.ndarray:
        """A full block-table row: the request's pages in logical order,
        trash-padded to ``pages_per_slot``."""
        row = np.full(self.pages_per_slot, TRASH_PAGE, np.int32)
        row[:len(pages)] = np.asarray(pages, np.int32)
        return row

    def as_dict(self) -> dict:
        out = {"num_pages": self.num_pages, "page_size": self.page_size,
               "pages_per_slot": self.pages_per_slot,
               "free_pages": self.free_pages,
               "used_pages": self.used_pages,
               "utilization": round(self.utilization(), 4),
               "prefix_index_size": len(self._prefix),
               "shared_pages_total": self.shared_pages,
               "fresh_pages_total": self.fresh_pages}
        if self.retention is not None:
            out["prefix_cache"] = self.retention.stats()
        return out
