"""Continuous-batching autoregressive serving with a paged KV cache.

The decode analog of ``deeplearning4j_tpu/serving/``: requests join and
leave a RUNNING decode batch at every step (iteration-level scheduling,
Orca/vLLM), KV state lives in fixed-size pages addressed through int32
block tables (closed XLA shape set, zero steady-state recompiles),
identical prompt prefixes share refcounted pages, the optional
persistent radix-tree prefix cache keeps prompt pages ALIVE across
requests (pinning, host-tier offload, cache-aware admission — see
``prefix_cache.py``), and the serving model hot-swaps between decode
steps with zero dropped streams.  See docs/serving.md ("Generation").
"""

from deeplearning4j_tpu.generation.engine import (      # noqa: F401
    DEFAULT_MODEL, GenerationEngine,
)
from deeplearning4j_tpu.generation.paged_cache import (  # noqa: F401
    PagedKVCache, PageExhaustedError,
)
from deeplearning4j_tpu.generation.prefix_cache import (  # noqa: F401
    PrefixCache, PrefixCacheConfig, StalePrefixError,
)
from deeplearning4j_tpu.generation.programs import (     # noqa: F401
    GenerationPrograms, seed_paged_pools,
)
from deeplearning4j_tpu.generation.scheduler import (    # noqa: F401
    DecodeScheduler, GenerationRequest,
)
