"""Sentence / document iterators (the corpus-ingest layer).

Reference: ``deeplearning4j-nlp/.../text/sentenceiterator/`` —
``BasicLineIterator`` (file, one sentence per line), ``LineSentenceIterator``,
``CollectionSentenceIterator``, ``AggregatingSentenceIterator``,
``FileSentenceIterator`` (every file in a dir), label-aware variants
(``LabelAwareSentenceIterator``, ``documentiterator/LabelledDocument``,
``LabelAwareIterator``).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Iterable, Iterator, List, Optional


class SentenceIterator:
    """≙ ``sentenceiterator/SentenceIterator.java`` — streaming corpus of
    sentences with reset; optional preprocessor applied per sentence."""

    def __init__(self):
        self.pre_processor = None

    def __iter__(self) -> Iterator[str]:
        self.reset()
        while self.has_next():
            yield self.next_sentence()

    def next_sentence(self) -> str:
        raise NotImplementedError

    def has_next(self) -> bool:
        raise NotImplementedError

    def reset(self) -> None:
        raise NotImplementedError

    def _apply(self, s: str) -> str:
        if self.pre_processor is not None:
            return self.pre_processor(s)
        return s


class CollectionSentenceIterator(SentenceIterator):
    """≙ ``CollectionSentenceIterator.java``."""

    def __init__(self, sentences: Iterable[str]):
        super().__init__()
        self._sentences = list(sentences)
        self._pos = 0

    def next_sentence(self) -> str:
        s = self._sentences[self._pos]
        self._pos += 1
        return self._apply(s)

    def has_next(self) -> bool:
        return self._pos < len(self._sentences)

    def reset(self) -> None:
        self._pos = 0


class BasicLineIterator(SentenceIterator):
    """One sentence per line from a file. ≙ ``BasicLineIterator.java``."""

    def __init__(self, path: str):
        super().__init__()
        self.path = path
        self._fh = None
        self._next: Optional[str] = None
        self.reset()

    def _advance(self):
        line = self._fh.readline()
        self._next = line.rstrip("\n") if line else None

    def next_sentence(self) -> str:
        s = self._next
        self._advance()
        return self._apply(s)

    def has_next(self) -> bool:
        return self._next is not None

    def reset(self) -> None:
        if self._fh is not None:
            self._fh.close()
        self._fh = open(self.path, "r", encoding="utf-8")
        self._advance()


class FileSentenceIterator(SentenceIterator):
    """Every line of every file under a directory.
    ≙ ``FileSentenceIterator.java``."""

    def __init__(self, root: str):
        super().__init__()
        self.root = root
        self.reset()

    def reset(self) -> None:
        paths = []
        if os.path.isdir(self.root):
            for dirpath, _, files in os.walk(self.root):
                for f in sorted(files):
                    paths.append(os.path.join(dirpath, f))
        else:
            paths = [self.root]
        self._lines: List[str] = []
        for p in paths:
            with open(p, "r", encoding="utf-8") as fh:
                self._lines.extend(line.rstrip("\n") for line in fh)
        self._pos = 0

    def next_sentence(self) -> str:
        s = self._lines[self._pos]
        self._pos += 1
        return self._apply(s)

    def has_next(self) -> bool:
        return self._pos < len(self._lines)


class AggregatingSentenceIterator(SentenceIterator):
    """Chains several iterators. ≙ ``AggregatingSentenceIterator.java``."""

    def __init__(self, *iterators: SentenceIterator):
        super().__init__()
        self._iterators = list(iterators)
        self.reset()

    def reset(self) -> None:
        for it in self._iterators:
            it.reset()
        self._idx = 0

    def _current(self) -> Optional[SentenceIterator]:
        while self._idx < len(self._iterators):
            if self._iterators[self._idx].has_next():
                return self._iterators[self._idx]
            self._idx += 1
        return None

    def has_next(self) -> bool:
        return self._current() is not None

    def next_sentence(self) -> str:
        return self._apply(self._current().next_sentence())


# --------------------------------------------------------------------------
# label-aware documents (ParagraphVectors input)
# --------------------------------------------------------------------------

@dataclass
class LabelledDocument:
    """≙ ``documentiterator/LabelledDocument.java``."""

    content: str
    labels: List[str] = field(default_factory=list)

    @property
    def label(self) -> Optional[str]:
        return self.labels[0] if self.labels else None


class LabelAwareIterator:
    """≙ ``documentiterator/LabelAwareIterator.java``."""

    def __iter__(self) -> Iterator[LabelledDocument]:
        self.reset()
        while self.has_next():
            yield self.next_document()

    def next_document(self) -> LabelledDocument:
        raise NotImplementedError

    def has_next(self) -> bool:
        raise NotImplementedError

    def reset(self) -> None:
        raise NotImplementedError


class SimpleLabelAwareIterator(LabelAwareIterator):
    """In-memory list of LabelledDocuments.
    ≙ ``documentiterator/SimpleLabelAwareIterator.java``."""

    def __init__(self, documents: Iterable[LabelledDocument]):
        self._docs = list(documents)
        self._pos = 0

    def next_document(self) -> LabelledDocument:
        d = self._docs[self._pos]
        self._pos += 1
        return d

    def has_next(self) -> bool:
        return self._pos < len(self._docs)

    def reset(self) -> None:
        self._pos = 0


class LabelsSource:
    """Generates/holds document labels. ≙ ``text/documentiterator/LabelsSource.java``."""

    def __init__(self, template: str = "DOC_%d"):
        self.template = template
        self.labels: List[str] = []
        self._counter = 0

    def next_label(self) -> str:
        label = self.template % self._counter
        self._counter += 1
        self.labels.append(label)
        return label

    def store_label(self, label: str) -> None:
        if label not in self.labels:
            self.labels.append(label)
