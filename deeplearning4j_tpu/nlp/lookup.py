"""Weight lookup table: syn0/syn1/syn1Neg + negative-sampling distribution.

Reference: ``models/embeddings/inmemory/InMemoryLookupTable.java:62-138`` —
``syn0`` (input embeddings), ``syn1`` (hierarchical-softmax inner nodes),
``syn1Neg`` (negative-sampling output weights), the unigram^0.75 sampling
table (``table``), and ``resetWeights`` init.

TPU redesign: matrices are ``jax.Array``s living in HBM; negative sampling
uses inverse-CDF ``searchsorted`` over the unigram^0.75 cumulative (no 100M
-entry table materialisation); all updates happen in the jitted batch kernels
(``nlp/learning.py``), never row-by-row from the host.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.vocab import VocabCache


class InMemoryLookupTable:
    def __init__(self, cache: VocabCache, vector_length: int,
                 seed: int = 12345, negative: float = 0.0,
                 use_hs: bool = True, use_adagrad: bool = False):
        self.cache = cache
        self.vector_length = int(vector_length)
        self.seed = seed
        self.negative = negative
        self.use_hs = use_hs
        self.use_adagrad = use_adagrad
        self.syn0: Optional[jax.Array] = None
        self.syn1: Optional[jax.Array] = None
        self.syn1neg: Optional[jax.Array] = None
        # per-row AdaGrad accumulators (reference uses per-element AdaGrad
        # when configured; we keep per-row-per-dim squared-grad sums)
        self.syn0_hist: Optional[jax.Array] = None
        self.syn1_hist: Optional[jax.Array] = None
        self.syn1neg_hist: Optional[jax.Array] = None
        self._neg_cdf: Optional[jax.Array] = None

    # ------------------------------------------------------------------ init
    def reset_weights(self) -> None:
        """≙ ``InMemoryLookupTable.resetWeights`` :133-138 (uniform in
        [-0.5/D, 0.5/D), syn1 zeros)."""
        V, D = len(self.cache), self.vector_length
        rs = np.random.RandomState(self.seed)
        self.syn0 = jnp.asarray(
            (rs.rand(V, D).astype(np.float32) - 0.5) / D)
        if self.use_hs:
            self.syn1 = jnp.zeros((V, D), jnp.float32)
        if self.negative > 0:
            self.syn1neg = jnp.zeros((V, D), jnp.float32)
        if self.use_adagrad:
            self.syn0_hist = jnp.zeros((V, D), jnp.float32)
            self.syn1_hist = jnp.zeros((V, D), jnp.float32) if self.use_hs else None
            self.syn1neg_hist = jnp.zeros((V, D), jnp.float32) if self.negative > 0 else None
        self._build_neg_cdf()

    def _build_neg_cdf(self) -> None:
        """Unigram^0.75 cumulative distribution for inverse-CDF sampling
        (replaces the reference's materialised ``table``)."""
        freqs = np.array([w.element_frequency for w in self.cache.vocab_words()],
                         np.float64)
        if len(freqs) == 0:
            self._neg_cdf = None
            return
        p = freqs ** 0.75
        p /= p.sum()
        self._neg_cdf = jnp.asarray(np.cumsum(p).astype(np.float32))

    def sample_negatives(self, key, shape) -> jax.Array:
        """Draw negative-sample word indices ~ unigram^0.75."""
        u = jax.random.uniform(key, shape)
        return jnp.searchsorted(self._neg_cdf, u).astype(jnp.int32)

    # ----------------------------------------------------------------- query
    def vector(self, label: str) -> Optional[np.ndarray]:
        idx = self.cache.index_of(label)
        if idx < 0 or self.syn0 is None:
            return None
        return np.asarray(self.syn0[idx])

    def put_vector(self, label: str, vec) -> None:
        idx = self.cache.index_of(label)
        if idx < 0:
            raise KeyError(label)
        self.syn0 = self.syn0.at[idx].set(jnp.asarray(vec, self.syn0.dtype))

    @property
    def num_words(self) -> int:
        return len(self.cache)
