"""Vocabulary: elements, cache, constructor, Huffman coding.

Reference: ``models/word2vec/wordstore/`` — ``VocabWord`` (a
``SequenceElement`` with frequency/index/Huffman codes),
``inmemory/AbstractCache`` (the vocab cache), ``VocabConstructor``
(parallel corpus scan + min-frequency pruning), and
``models/word2vec/Huffman.java:34`` (tree build assigning codes/points).

TPU note: codes/points are materialised as dense padded numpy arrays
(``codes_matrix``) so the hierarchical-softmax path is one gather per batch
instead of per-word ragged walks.
"""

from __future__ import annotations

import heapq
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence as Seq

import numpy as np


@dataclass
class SequenceElement:
    """≙ ``sequencevectors/sequence/SequenceElement.java`` — the generic
    trainable element (word, graph vertex, document label...)."""

    label: str
    element_frequency: float = 1.0
    index: int = -1
    # Huffman coding (hierarchical softmax)
    codes: List[int] = field(default_factory=list)
    points: List[int] = field(default_factory=list)
    # ParagraphVectors marks label elements specially
    special: bool = False

    def increment_frequency(self, by: float = 1.0) -> None:
        self.element_frequency += by


class VocabWord(SequenceElement):
    """≙ ``models/word2vec/VocabWord.java``."""


@dataclass
class Sequence:
    """An ordered run of elements (sentence, walk, document).
    ≙ ``sequencevectors/sequence/Sequence.java``."""

    elements: List[SequenceElement] = field(default_factory=list)
    labels: List[SequenceElement] = field(default_factory=list)

    def add_element(self, el: SequenceElement) -> None:
        self.elements.append(el)

    def set_sequence_label(self, el: SequenceElement) -> None:
        self.labels = [el]

    @property
    def sequence_label(self) -> Optional[SequenceElement]:
        return self.labels[0] if self.labels else None


class VocabCache:
    """In-memory vocab. ≙ ``wordstore/inmemory/AbstractCache.java``."""

    def __init__(self):
        self._by_label: Dict[str, SequenceElement] = {}
        self._by_index: List[SequenceElement] = []
        self.total_word_count: float = 0.0

    # -- build
    def add_token(self, el: SequenceElement) -> SequenceElement:
        cur = self._by_label.get(el.label)
        if cur is None:
            self._by_label[el.label] = el
            return el
        cur.increment_frequency(el.element_frequency)
        return cur

    def finalize_vocab(self) -> None:
        """Assign indices by descending frequency (ties: label order) and
        recompute totals."""
        elements = sorted(self._by_label.values(),
                          key=lambda e: (-e.element_frequency, e.label))
        self._by_index = elements
        for i, el in enumerate(elements):
            el.index = i
        self.total_word_count = float(sum(e.element_frequency for e in elements
                                          if not e.special))

    # -- query
    def contains_word(self, label: str) -> bool:
        return label in self._by_label

    def word_for(self, label: str) -> Optional[SequenceElement]:
        return self._by_label.get(label)

    def element_at_index(self, idx: int) -> SequenceElement:
        return self._by_index[idx]

    def index_of(self, label: str) -> int:
        el = self._by_label.get(label)
        return -1 if el is None else el.index

    def word_frequency(self, label: str) -> float:
        el = self._by_label.get(label)
        return 0.0 if el is None else el.element_frequency

    def num_words(self) -> int:
        return len(self._by_label)

    def words(self) -> List[str]:
        return [e.label for e in self._by_index]

    def vocab_words(self) -> List[SequenceElement]:
        return list(self._by_index)

    def __len__(self) -> int:
        return len(self._by_label)


class VocabConstructor:
    """Corpus scan → counted, pruned, index-assigned vocab.
    ≙ ``wordstore/VocabConstructor.java`` (buildJointVocabulary).
    """

    def __init__(self, min_element_frequency: float = 1.0,
                 element_cls=VocabWord):
        self.min_element_frequency = min_element_frequency
        self.element_cls = element_cls

    def build_vocab(self, sequences: Iterable[Sequence],
                    cache: Optional[VocabCache] = None) -> VocabCache:
        cache = cache or VocabCache()
        n_sequences = 0
        for seq in sequences:
            n_sequences += 1
            for el in seq.elements:
                label = el.label if isinstance(el, SequenceElement) else str(el)
                cache.add_token(self.element_cls(label=label))
            for lab in seq.labels:
                held = cache.add_token(self.element_cls(label=lab.label, special=True))
                held.special = True
        # prune below min frequency (labels/special elements are kept)
        for label in [e.label for e in cache._by_label.values()
                      if not e.special and e.element_frequency < self.min_element_frequency]:
            del cache._by_label[label]
        cache.finalize_vocab()
        return cache


def build_huffman(cache: VocabCache) -> None:
    """Huffman-code the vocab in place: frequent words get short codes.
    ≙ ``models/word2vec/Huffman.java:34``.

    After this, each element has ``codes`` (bit path, 0/1) and ``points``
    (inner-node ids usable as rows of ``syn1``).
    """
    words = cache.vocab_words()
    V = len(words)
    if V == 0:
        return
    # heap of (freq, tiebreak, node_id); leaves are 0..V-1, inner V..2V-2
    heap = [(w.element_frequency, i, i) for i, w in enumerate(words)]
    heapq.heapify(heap)
    parent = {}
    binary = {}
    next_id = V
    while len(heap) > 1:
        f1, _, n1 = heapq.heappop(heap)
        f2, _, n2 = heapq.heappop(heap)
        parent[n1], parent[n2] = next_id, next_id
        binary[n1], binary[n2] = 0, 1
        heapq.heappush(heap, (f1 + f2, next_id, next_id))
        next_id += 1
    root = heap[0][2] if heap else None
    for i, w in enumerate(words):
        codes, points = [], []
        node = i
        while node != root and node in parent:
            codes.append(binary[node])
            points.append(parent[node] - V)  # inner-node row in syn1
            node = parent[node]
        codes.reverse()
        points.reverse()
        w.codes = codes
        w.points = points


def codes_matrix(cache: VocabCache):
    """Dense padded (codes, points, lengths) arrays for batched HS.
    Rows align with vocab indices.  Padding rows point at inner node 0 with
    length-masked contributions."""
    words = cache.vocab_words()
    V = len(words)
    L = max((len(w.codes) for w in words), default=1) or 1
    codes = np.zeros((V, L), np.float32)
    points = np.zeros((V, L), np.int32)
    lengths = np.zeros((V,), np.int32)
    for i, w in enumerate(words):
        n = len(w.codes)
        lengths[i] = n
        codes[i, :n] = w.codes
        points[i, :n] = w.points
    return codes, points, lengths
