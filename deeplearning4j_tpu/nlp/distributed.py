"""Distributed embedding training — the Spark-NLP scaleout redesigned.

Reference: ``spark/dl4j-spark-nlp/.../word2vec/Word2Vec.java:61,130-195`` —
TextPipeline builds the vocab with Spark accumulators, the driver broadcasts
vocab + exp table, executors run First/SecondIterationFunction over their
partitions, and syn0 is averaged across partitions at the end.

TPU-native redesign: no driver/executor split and no parameter shipping.
 * vocab build: multithreaded host-side counting (the accumulator analog);
 * training: every pair batch is sharded over the mesh 'data' axis with
   ``shard_map``; each device runs the SAME batched kernel
   (``nlp/learning.py``: gather → MXU einsum → scatter-add) on its shard and
   the resulting parameter deltas are ``pmean``-ed over ICI — the
   per-partition-average semantics of the reference, applied every batch
   instead of once per epoch, so quality matches single-process training;
 * determinism: same seed ⇒ same pair stream ⇒ same result for any mesh
   size whose pmean ordering is fixed (XLA all-reduce is deterministic).

``DistributedWord2Vec`` on a 1-device mesh reproduces ``Word2Vec`` exactly
(the equivalence oracle, ≙ TestSparkWord2Vec-style parity).
"""

from __future__ import annotations

import math
import threading
from collections import Counter
from functools import partial
from typing import Iterable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from deeplearning4j_tpu.backend.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from deeplearning4j_tpu.backend import device as backend
from deeplearning4j_tpu.nlp import learning
from deeplearning4j_tpu.nlp.documents import SentenceIterator
from deeplearning4j_tpu.nlp.sequencevectors import VectorsConfiguration
from deeplearning4j_tpu.nlp.tokenization import (
    DefaultTokenizerFactory, TokenizerFactory,
)
from deeplearning4j_tpu.nlp.vocab import VocabCache, VocabWord
from deeplearning4j_tpu.nlp.glove import Glove
from deeplearning4j_tpu.nlp.word2vec import Word2Vec


def parallel_vocab_count(sentences: List[str],
                         tokenizer_factory: TokenizerFactory,
                         n_threads: int = 4) -> Counter:
    """Multithreaded token counting — the TextPipeline accumulator analog
    (``spark/text/functions/TextPipeline.java``)."""
    chunks = np.array_split(np.asarray(sentences, dtype=object),
                            max(n_threads, 1))
    counters = [Counter() for _ in chunks]

    def count(i):
        tf = tokenizer_factory
        for s in chunks[i]:
            counters[i].update(tf.create(str(s)).tokens())

    threads = [threading.Thread(target=count, args=(i,))
               for i in range(len(chunks))]
    [t.start() for t in threads]
    [t.join() for t in threads]
    total = Counter()
    for c in counters:
        total.update(c)
    return total


class DistributedWord2Vec(Word2Vec):
    """Word2Vec whose batch kernel runs SPMD over a device mesh.

    Batches are zero-padded to a multiple of the mesh's 'data' axis size;
    padded rows carry mask 0 so they contribute nothing (same masking the
    serial engine uses for its power-of-two padding).
    """

    def __init__(self, config: VectorsConfiguration,
                 sentence_iterator: SentenceIterator,
                 tokenizer_factory: Optional[TokenizerFactory] = None,
                 mesh: Optional[Mesh] = None):
        super().__init__(config, sentence_iterator,
                         tokenizer_factory or DefaultTokenizerFactory())
        self.mesh = mesh or backend.default_mesh()
        axis = self.mesh.axis_names[0]
        self._axis = axis
        self._sharded_steps = {}

    # Which rows of each parameter matrix a kernel touches, and with what
    # occurrence weights — needed to convert per-shard collision-mean deltas
    # back into the exact global mean (see _get_sharded).
    @staticmethod
    def _row_specs(name, sharded):
        def bcast(mask, idx2d):
            return jnp.broadcast_to(mask[:, None], idx2d.shape).reshape(-1)

        if name == "sg_ns":
            inputs, targets, negs, mask = sharded
            out = jnp.concatenate([targets[:, None], negs], 1)
            return (inputs, mask), (out.reshape(-1), bcast(mask, out))
        if name == "sg_hs":
            inputs, pts, _cds, code_mask, mask = sharded
            return ((inputs, mask),
                    (pts.reshape(-1), (code_mask * mask[:, None]).reshape(-1)))
        if name == "cbow_ns":
            ctx, ctx_mask, targets, negs, mask = sharded
            out = jnp.concatenate([targets[:, None], negs], 1)
            return ((jnp.maximum(ctx, 0).reshape(-1),
                     (ctx_mask * mask[:, None]).reshape(-1)),
                    (out.reshape(-1), bcast(mask, out)))
        if name == "cbow_hs":
            ctx, ctx_mask, pts, _cds, code_mask, mask = sharded
            return ((jnp.maximum(ctx, 0).reshape(-1),
                     (ctx_mask * mask[:, None]).reshape(-1)),
                    (pts.reshape(-1), (code_mask * mask[:, None]).reshape(-1)))
        raise KeyError(name)

    def _get_sharded(self, name, fn, n_sharded_args):
        """shard_map-wrap one of the learning-step kernels.

        Params stay replicated; batch args shard over the data axis.  The
        kernels apply a collision-MEAN per row over their (local) batch, so
        the per-shard delta is  sum_local/count_local.  Multiplying back by
        the local count, psum-ing both sums and counts over ICI, and
        re-dividing yields  Σsums/Σcounts — the identical update serial
        training computes on the unsharded batch (distributed == local
        math, the reference's equivalence oracle)."""
        key = name
        if key in self._sharded_steps:
            return self._sharded_steps[key]
        axis = self._axis
        mesh = self.mesh
        specs = self._row_specs
        in_specs = (P(), P()) + (P(axis),) * n_sharded_args + (P(),)
        out_specs = (P(), P(), P())

        @partial(shard_map, mesh=mesh, in_specs=in_specs,
                 out_specs=out_specs)
        def stepped(a, b, *rest):
            *sharded, lr = rest
            new_a, new_b, loss = fn(a, b, *sharded, lr)
            (ia, wa), (ib, wb) = specs(name, sharded)
            ca = jnp.zeros((a.shape[0],), a.dtype).at[ia].add(wa)
            cb = jnp.zeros((b.shape[0],), b.dtype).at[ib].add(wb)
            ca_tot = jax.lax.psum(ca, axis)
            cb_tot = jax.lax.psum(cb, axis)
            da = (jax.lax.psum((new_a - a) * ca[:, None], axis)
                  / jnp.maximum(ca_tot, 1.0)[:, None])
            db = (jax.lax.psum((new_b - b) * cb[:, None], axis)
                  / jnp.maximum(cb_tot, 1.0)[:, None])
            return a + da, b + db, jax.lax.psum(loss, axis)

        jitted = jax.jit(stepped)
        self._sharded_steps[key] = jitted
        return jitted

    def _pad_to_devices(self, n: int) -> int:
        """Global batch size: power-of-two >= n AND divisible by mesh size."""
        ndev = self.mesh.devices.size
        B = max(self.config.batch_size,
                int(2 ** math.ceil(math.log2(max(n, 1)))))
        return int(np.ceil(B / ndev) * ndev)

    def _apply_batch(self, batch, lr) -> None:
        cfg = self.config
        lk = self.lookup
        n = len(batch["targets"])
        if n == 0:
            return
        B = self._pad_to_devices(n)
        mask = jnp.asarray(self._pad(np.ones(n, np.float32), B))
        targets = jnp.asarray(self._pad(batch["targets"], B))
        lr = jnp.float32(lr)
        if batch["kind"] == "sg":
            inputs = jnp.asarray(self._pad(batch["inputs"], B))
            if cfg.negative > 0:
                negs = lk.sample_negatives(self._next_key(), (B, cfg.negative))
                step = self._get_sharded("sg_ns", learning.sg_ns_step, 4)
                lk.syn0, lk.syn1neg, loss = step(
                    lk.syn0, lk.syn1neg, inputs, targets, negs, mask, lr)
                self.cum_loss += float(loss)
            if cfg.use_hierarchic_softmax:
                pts = jnp.asarray(self._points)[targets]
                cds = jnp.asarray(self._codes)[targets]
                ln = jnp.asarray(self._code_lengths)[targets]
                code_mask = (jnp.arange(self._codes.shape[1])[None, :]
                             < ln[:, None]).astype(jnp.float32)
                step = self._get_sharded("sg_hs", learning.sg_hs_step, 5)
                lk.syn0, lk.syn1, loss = step(
                    lk.syn0, lk.syn1, inputs, pts, cds, code_mask, mask, lr)
                self.cum_loss += float(loss)
        else:  # cbow
            ctx = jnp.asarray(self._pad(batch["contexts"], B, fill=-1))
            ctx_mask = (ctx >= 0).astype(jnp.float32)
            if cfg.negative > 0:
                negs = lk.sample_negatives(self._next_key(), (B, cfg.negative))
                step = self._get_sharded("cbow_ns", learning.cbow_ns_step, 5)
                lk.syn0, lk.syn1neg, loss = step(
                    lk.syn0, lk.syn1neg, ctx, ctx_mask, targets, negs, mask,
                    lr)
                self.cum_loss += float(loss)
            if cfg.use_hierarchic_softmax:
                pts = jnp.asarray(self._points)[targets]
                cds = jnp.asarray(self._codes)[targets]
                ln = jnp.asarray(self._code_lengths)[targets]
                code_mask = (jnp.arange(self._codes.shape[1])[None, :]
                             < ln[:, None]).astype(jnp.float32)
                step = self._get_sharded("cbow_hs", learning.cbow_hs_step, 6)
                lk.syn0, lk.syn1, loss = step(
                    lk.syn0, lk.syn1, ctx, ctx_mask, pts, cds, code_mask,
                    mask, lr)
                self.cum_loss += float(loss)

    class Builder(Word2Vec.Builder):
        def __init__(self):
            super().__init__()
            self._mesh = None

        def mesh(self, mesh: Mesh) -> "DistributedWord2Vec.Builder":
            self._mesh = mesh
            return self

        def build(self) -> "DistributedWord2Vec":
            w2v = super().build()
            return DistributedWord2Vec(
                w2v.config, w2v.sentence_iterator, w2v.tokenizer_factory,
                mesh=self._mesh)


class DistributedGlove(Glove):
    """GloVe whose weighted-least-squares batches shard over the mesh.

    ≙ ``spark/dl4j-spark-nlp/.../glove/Glove.java`` (partition-parallel
    training with per-partition averaging).  Co-occurrence triples shard
    over the data axis; each shard runs the AdaGrad kernel on its slice and
    the parameter/accumulator deltas are pmean-ed — the reference's
    partition-averaged semantics per batch (AdaGrad's nonlinearity makes
    exact serial equivalence impossible here, as it was for Spark)."""

    def __init__(self, *args, mesh: Optional[Mesh] = None, **kw):
        super().__init__(*args, **kw)
        self.mesh = mesh or backend.default_mesh()
        axis = self.mesh.axis_names[0]
        ndev = self.mesh.shape[axis]
        if self.batch_size % ndev:
            self.batch_size = int(np.ceil(self.batch_size / ndev) * ndev)
        mesh_ = self.mesh

        @partial(shard_map, mesh=mesh_,
                 in_specs=(P(),) * 8 + (P(axis),) * 4 + (P(),) * 3,
                 out_specs=(P(),) * 9)
        def stepped(w, wc, b, bc, hw, hwc, hb, hbc, rows, cols, xij, mask,
                    lr, x_max, alpha):
            outs = learning.glove_step(w, wc, b, bc, hw, hwc, hb, hbc,
                                       rows, cols, xij, mask, lr, x_max,
                                       alpha)
            *new_state, loss = outs
            old = (w, wc, b, bc, hw, hwc, hb, hbc)
            averaged = tuple(
                o + jax.lax.pmean(n - o, axis)
                for o, n in zip(old, new_state))
            return averaged + (jax.lax.psum(loss, axis),)

        self._glove_step = jax.jit(stepped)

    class Builder(Glove.Builder):
        def __init__(self):
            super().__init__()
            self._mesh = None

        def mesh(self, mesh: Mesh) -> "DistributedGlove.Builder":
            self._mesh = mesh
            return self

        def build(self) -> "DistributedGlove":
            g = super().build()
            return DistributedGlove(
                sentence_iterator=g.sentence_iterator,
                tokenizer_factory=g.tokenizer_factory,
                layer_size=g.layer_size, window=g.window, epochs=g.epochs,
                learning_rate=g.learning_rate, x_max=g.x_max, alpha=g.alpha,
                min_word_frequency=g.min_word_frequency,
                batch_size=g.batch_size, seed=g.seed, symmetric=g.symmetric,
                mesh=self._mesh)
