"""WordVectorSerializer: Google word2vec text/binary formats + zip model.

Reference: ``models/embeddings/loader/WordVectorSerializer.java`` (~2k LoC):
``writeWordVectors`` (text: header "V D", then "word f1 f2 ..."),
Google binary format (header line, then ``word<space><D float32 LE>``),
``writeFullModel``/zip round trip of vocab + syn0/syn1 + config.
"""

from __future__ import annotations

import io
import json
import struct
import zipfile
from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.lookup import InMemoryLookupTable
from deeplearning4j_tpu.nlp.vocab import VocabCache, VocabWord, build_huffman
from deeplearning4j_tpu.nlp.word2vec import StaticWord2Vec


# ----------------------------------------------------------------- text fmt

def write_word_vectors(model, path: str) -> None:
    """Google/gensim text format."""
    vocab, lookup = model.vocab, model.lookup
    syn0 = np.asarray(lookup.syn0)
    with open(path, "w", encoding="utf-8") as f:
        f.write(f"{len(vocab)} {lookup.vector_length}\n")
        for el in vocab.vocab_words():
            vec = " ".join(f"{x:.6f}" for x in syn0[el.index])
            f.write(f"{el.label} {vec}\n")


def read_word_vectors(path: str) -> StaticWord2Vec:
    """Reads the text format into a query-only model (file order = index
    order, as the reference loader preserves it)."""
    cache = VocabCache()
    rows = []
    with open(path, "r", encoding="utf-8") as f:
        header = f.readline().split()
        V, D = int(header[0]), int(header[1])
        for line in f:
            parts = line.rstrip("\n").split(" ")
            if len(parts) < D + 1:
                continue
            cache.add_token(VocabWord(label=parts[0]))
            rows.append(np.array(parts[1:D + 1], np.float32))
    order = list(cache._by_label.values())
    for i, el in enumerate(order):
        el.index = i
    cache._by_index = order
    cache.total_word_count = float(len(order))
    lookup = InMemoryLookupTable(cache, D, use_hs=False)
    lookup.syn0 = jnp.asarray(np.stack(rows))
    lookup._build_neg_cdf()
    return StaticWord2Vec(cache, lookup)


# --------------------------------------------------------------- binary fmt

def write_binary(model, path: str) -> None:
    """Google word2vec binary format (header text line; per word: label,
    space, D little-endian float32, newline)."""
    vocab, lookup = model.vocab, model.lookup
    syn0 = np.asarray(lookup.syn0, np.float32)
    with open(path, "wb") as f:
        f.write(f"{len(vocab)} {lookup.vector_length}\n".encode())
        for el in vocab.vocab_words():
            f.write(el.label.encode("utf-8") + b" ")
            f.write(syn0[el.index].astype("<f4").tobytes())
            f.write(b"\n")


def read_binary(path: str) -> StaticWord2Vec:
    with open(path, "rb") as f:
        header = f.readline().decode().split()
        V, D = int(header[0]), int(header[1])
        cache = VocabCache()
        rows = []
        order = []
        for _ in range(V):
            word_bytes = bytearray()
            while True:
                ch = f.read(1)
                if ch == b" " or ch == b"":
                    break
                word_bytes.extend(ch)
            word = word_bytes.decode("utf-8").lstrip("\n")
            vec = np.frombuffer(f.read(4 * D), dtype="<f4").astype(np.float32)
            f.read(1)  # trailing newline
            el = cache.add_token(VocabWord(label=word))
            order.append(el)
            rows.append(vec)
    for i, el in enumerate(order):
        el.index = i
    cache._by_index = order
    cache.total_word_count = float(V)
    lookup = InMemoryLookupTable(cache, D, use_hs=False)
    lookup.syn0 = jnp.asarray(np.stack(rows))
    lookup._build_neg_cdf()
    return StaticWord2Vec(cache, lookup)


# ------------------------------------------------------------------ zip fmt

def write_full_model(model, path: str) -> None:
    """Zip container: vocab.json (labels/freqs/codes) + syn0/syn1/syn1neg
    npy + config.json.  ≙ ``WordVectorSerializer.writeFullModel``."""
    vocab, lookup = model.vocab, model.lookup
    cfg = getattr(model, "config", None)
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as zf:
        vocab_rec = [{
            "label": el.label,
            "frequency": el.element_frequency,
            "index": el.index,
            "codes": el.codes,
            "points": el.points,
            "special": el.special,
        } for el in vocab.vocab_words()]
        zf.writestr("vocab.json", json.dumps(vocab_rec))
        meta = {
            "vector_length": lookup.vector_length,
            "negative": lookup.negative,
            "use_hs": lookup.use_hs,
            "total_word_count": vocab.total_word_count,
        }
        if cfg is not None:
            meta["config"] = {k: getattr(cfg, k) for k in (
                "layer_size", "window", "negative", "use_hierarchic_softmax",
                "min_word_frequency", "epochs", "learning_rate", "seed",
                "elements_algorithm")}
        zf.writestr("config.json", json.dumps(meta))

        def put(name, arr):
            if arr is None:
                return
            buf = io.BytesIO()
            np.save(buf, np.asarray(arr))
            zf.writestr(name, buf.getvalue())

        put("syn0.npy", lookup.syn0)
        put("syn1.npy", lookup.syn1)
        put("syn1neg.npy", lookup.syn1neg)


def read_full_model(path: str) -> StaticWord2Vec:
    with zipfile.ZipFile(path, "r") as zf:
        vocab_rec = json.loads(zf.read("vocab.json").decode())
        meta = json.loads(zf.read("config.json").decode())
        cache = VocabCache()
        order = []
        for rec in vocab_rec:
            el = VocabWord(label=rec["label"],
                           element_frequency=rec["frequency"],
                           index=rec["index"], special=rec.get("special", False))
            el.codes = rec.get("codes", [])
            el.points = rec.get("points", [])
            cache._by_label[el.label] = el
            order.append(el)
        order.sort(key=lambda e: e.index)
        cache._by_index = order
        cache.total_word_count = meta.get("total_word_count",
                                          float(len(order)))

        def get(name):
            try:
                return jnp.asarray(np.load(io.BytesIO(zf.read(name))))
            except KeyError:
                return None

        lookup = InMemoryLookupTable(cache, meta["vector_length"],
                                     negative=meta.get("negative", 0),
                                     use_hs=meta.get("use_hs", True))
        lookup.syn0 = get("syn0.npy")
        lookup.syn1 = get("syn1.npy")
        lookup.syn1neg = get("syn1neg.npy")
        lookup._build_neg_cdf()
    return StaticWord2Vec(cache, lookup)
