"""Tokenizers + token preprocessing.

Reference: ``deeplearning4j-nlp/.../text/tokenization/`` — DefaultTokenizer
(whitespace/punct split via java.util.StringTokenizer semantics),
NGramTokenizer, ``CommonPreprocessor`` (lowercase + strip punctuation),
``EndingPreProcessor``, stopwords list (``text/stopwords``).
"""

from __future__ import annotations

import re
from typing import Callable, List, Optional

# Compact english stopword list (reference ships one as a resource file;
# text/stopwords — same role, trimmed to the common core).
# single authoritative stoplist (see nlp/stopwords.py, ≙ StopWords.java)
from deeplearning4j_tpu.nlp.stopwords import ENGLISH as STOP_WORDS


class TokenPreProcess:
    """≙ ``tokenization/tokenizer/TokenPreProcess.java``."""

    def pre_process(self, token: str) -> str:
        raise NotImplementedError


class CommonPreprocessor(TokenPreProcess):
    """Lowercase + strip punctuation/digits at token edges.
    ≙ ``preprocessor/CommonPreprocessor.java``."""

    _PUNCT = re.compile(r"[\d\.:,\"'\(\)\[\]|/?!;]+")

    def pre_process(self, token: str) -> str:
        return self._PUNCT.sub("", token.lower())


class EndingPreProcessor(TokenPreProcess):
    """Crude stemmer: strips common english endings.
    ≙ ``preprocessor/EndingPreProcessor.java``."""

    def pre_process(self, token: str) -> str:
        if token.endswith("s") and not token.endswith("ss"):
            token = token[:-1]
        for suffix in ("ed", "ing", "ly"):
            if token.endswith(suffix):
                token = token[: -len(suffix)]
                break
        return token


class Tokenizer:
    """≙ ``tokenization/tokenizer/Tokenizer.java`` — iterator surface kept
    pythonic: ``tokens()`` returns the full list."""

    def __init__(self, tokens: List[str], pre: Optional[TokenPreProcess] = None):
        self._tokens = tokens
        self._pre = pre

    def set_token_pre_processor(self, pre: TokenPreProcess) -> None:
        self._pre = pre

    def count_tokens(self) -> int:
        return len(self.tokens())

    def tokens(self) -> List[str]:
        out = []
        for t in self._tokens:
            if self._pre is not None:
                t = self._pre.pre_process(t)
            if t:
                out.append(t)
        return out


class TokenizerFactory:
    """≙ ``tokenizerfactory/TokenizerFactory.java``."""

    def create(self, text: str) -> Tokenizer:
        raise NotImplementedError

    def set_token_pre_processor(self, pre: TokenPreProcess) -> None:
        self._pre = pre


class DefaultTokenizerFactory(TokenizerFactory):
    """Whitespace tokenizer. ≙ ``DefaultTokenizerFactory.java``."""

    def __init__(self):
        self._pre: Optional[TokenPreProcess] = None

    def create(self, text: str) -> Tokenizer:
        return Tokenizer(text.split(), self._pre)


class NGramTokenizerFactory(TokenizerFactory):
    """Word n-grams over the base tokenization.
    ≙ ``NGramTokenizerFactory.java``."""

    def __init__(self, min_n: int, max_n: int,
                 base: Optional[TokenizerFactory] = None):
        self.min_n = min_n
        self.max_n = max_n
        self.base = base or DefaultTokenizerFactory()
        self._pre: Optional[TokenPreProcess] = None

    def create(self, text: str) -> Tokenizer:
        base_tokens = self.base.create(text).tokens()
        out = []
        for n in range(self.min_n, self.max_n + 1):
            for i in range(len(base_tokens) - n + 1):
                out.append(" ".join(base_tokens[i:i + n]))
        return Tokenizer(out, self._pre)
