"""Text annotation — the UIMA add-on's capabilities, dependency-free.

Reference: ``deeplearning4j-nlp-uima/`` (UIMA analysis engines wrapping
sentence segmentation, tokenization, POS tagging, and SentiWordNet
sentiment).  UIMA itself is JVM infrastructure, not capability; the
equivalents here are lightweight rule/lexicon annotators with the same
surface: annotate text -> sentences -> tokens with POS + sentiment scores.
"""

from __future__ import annotations

import dataclasses
import re
import string
from typing import Dict, List, Optional, Tuple

def _norm(token: str) -> str:
    """Lowercase + strip surrounding punctuation (the default tokenizer
    keeps sentence-final punctuation attached)."""
    return token.lower().strip(string.punctuation)

# --------------------------------------------------------------- sentences

# NB deliberately excludes "no": sentence-final "no." (the word) is far more
# common than the numeric abbreviation "No. 5"
_ABBREV = frozenset([
    "mr", "mrs", "ms", "dr", "prof", "sr", "jr", "st", "vs", "etc", "eg",
    "ie", "inc", "ltd", "co", "corp", "vol", "fig", "al",
])

_SENT_BOUNDARY = re.compile(r"([.!?]+)(\s+|$)")


def split_sentences(text: str) -> List[str]:
    """Rule-based sentence segmentation (≙ UIMA SentenceAnnotator):
    terminal punctuation ends a sentence unless it follows a known
    abbreviation or a single initial."""
    sentences: List[str] = []
    start = 0
    for m in _SENT_BOUNDARY.finditer(text):
        prev = text[start:m.start()].rstrip()
        last_word = (prev.split()[-1].lower().replace(".", "")
                     if prev.split() else "")
        if m.group(1).startswith(".") and (
                last_word in _ABBREV or (len(last_word) == 1
                                         and last_word.isalpha())):
            continue  # abbreviation / initial: not a boundary
        chunk = text[start:m.end()].strip()
        if chunk:
            sentences.append(chunk)
        start = m.end()
    tail = text[start:].strip()
    if tail:
        sentences.append(tail)
    return sentences


# --------------------------------------------------------------------- POS

# Closed-class lexicon + suffix rules: the capability analog of the UIMA/
# OpenNLP tagger for the pipelines the reference builds (token filtering,
# lemmatization hooks) — not a treebank-trained model.
_LEXICON: Dict[str, str] = {}
for _w in ("the a an this that these those my your his her its our their".split()):
    _LEXICON[_w] = "DET"
for _w in ("i you he she it we they me him us them who".split()):
    _LEXICON[_w] = "PRON"
for _w in ("in on at by for with from to of about over under into".split()):
    _LEXICON[_w] = "ADP"
for _w in ("and or but nor so yet".split()):
    _LEXICON[_w] = "CONJ"
for _w in ("is am are was were be been being have has had do does did "
           "will would can could shall should may might must".split()):
    _LEXICON[_w] = "VERB"
# common irregular past/base forms the suffix rules can't catch — only
# forms that are UNAMBIGUOUSLY verbal (homographs like left/saw/found/
# read/made/felt would mis-tag noun/adjective uses and fragment NPs)
for _w in ("ran run sat went goes take got came come said say told tell "
           "gave give knew know thought think kept held heard met brought "
           "began wrote".split()):
    _LEXICON[_w] = "VERB"
for _w in ("not never also very too quite really".split()):
    _LEXICON[_w] = "ADV"

_SUFFIX_RULES: List[Tuple[str, str]] = [
    ("ing", "VERB"), ("ed", "VERB"), ("ly", "ADV"),
    ("ous", "ADJ"), ("ful", "ADJ"), ("ive", "ADJ"), ("able", "ADJ"),
    ("ible", "ADJ"), ("al", "ADJ"), ("ness", "NOUN"), ("ment", "NOUN"),
    ("tion", "NOUN"), ("sion", "NOUN"), ("ity", "NOUN"), ("er", "NOUN"),
    ("ist", "NOUN"), ("ism", "NOUN"), ("s", "NOUN"),
]


def pos_tag(tokens: List[str]) -> List[Tuple[str, str]]:
    """(token, tag) pairs over the universal-ish tagset
    DET/PRON/ADP/CONJ/VERB/ADV/ADJ/NOUN/NUM/PUNCT."""
    out = []
    for tok in tokens:
        low = _norm(tok)
        if not any(c.isalnum() for c in tok):
            tag = "PUNCT"
        elif low.replace(".", "").replace(",", "").isdigit():
            tag = "NUM"
        elif low in _LEXICON:
            tag = _LEXICON[low]
        else:
            tag = "NOUN"
            for suffix, t in _SUFFIX_RULES:
                if len(low) > len(suffix) + 2 and low.endswith(suffix):
                    tag = t
                    break
        out.append((tok, tag))
    return out


# --------------------------------------------------------------- sentiment

# Compact polarity lexicon (SentiWordNet-style scores in [-1, 1]).
_SENTIMENT: Dict[str, float] = {
    "good": 0.7, "great": 0.8, "excellent": 0.9, "best": 0.9, "love": 0.8,
    "loved": 0.8, "wonderful": 0.8, "amazing": 0.8, "happy": 0.7,
    "fantastic": 0.8, "nice": 0.5, "perfect": 0.9, "better": 0.4,
    "awesome": 0.8, "enjoy": 0.6, "enjoyed": 0.6, "like": 0.4,
    "bad": -0.7, "terrible": -0.9, "awful": -0.9, "worst": -0.9,
    "hate": -0.8, "hated": -0.8, "horrible": -0.8, "sad": -0.6,
    "poor": -0.5, "disappointing": -0.7, "disappointed": -0.7,
    "worse": -0.5, "boring": -0.6, "annoying": -0.6, "broken": -0.5,
    "fail": -0.6, "failed": -0.6, "wrong": -0.4, "problem": -0.3,
}
_NEGATORS = frozenset(["not", "no", "never", "n't", "dont", "don't",
                       "didnt", "didn't", "isnt", "isn't", "wasnt",
                       "wasn't", "cant", "can't"])


def sentiment_score(tokens: List[str]) -> float:
    """Mean polarity over matched tokens, sign-flipped within 2 tokens of a
    negator (≙ the UIMA SentiWordNet annotator's aggregate use)."""
    scores = []
    for i, tok in enumerate(tokens):
        s = _SENTIMENT.get(_norm(tok))
        if s is None:
            continue
        window = [_norm(t) for t in tokens[max(0, i - 2):i]]
        if any(w in _NEGATORS for w in window):
            s = -s
        scores.append(s)
    return float(sum(scores) / len(scores)) if scores else 0.0


# --------------------------------------------------------------- annotator

@dataclasses.dataclass
class AnnotatedToken:
    text: str
    pos: str


@dataclasses.dataclass
class AnnotatedSentence:
    text: str
    tokens: List[AnnotatedToken]
    sentiment: float


class TextAnnotator:
    """Pipeline facade: text -> annotated sentences.  ≙ the UIMA analysis
    engine chain (sentence -> tokenize -> POS -> sentiment)."""

    def __init__(self, tokenizer_factory=None):
        if tokenizer_factory is None:
            from deeplearning4j_tpu.nlp.tokenization import (
                DefaultTokenizerFactory,
            )
            tokenizer_factory = DefaultTokenizerFactory()
        self.tokenizer_factory = tokenizer_factory

    def annotate(self, text: str) -> List[AnnotatedSentence]:
        out = []
        for sent in split_sentences(text):
            tokens = self.tokenizer_factory.create(sent).tokens()
            tagged = pos_tag(tokens)
            out.append(AnnotatedSentence(
                text=sent,
                tokens=[AnnotatedToken(t, p) for t, p in tagged],
                sentiment=sentiment_score(tokens),
            ))
        return out
