"""Constituency tree parsing + tree vectorization (the UIMA add-on's last
capability analog).

Reference: ``deeplearning4j-nlp-uima/.../treeparser/`` —
``TreeParser.java:60`` (text -> sentence segmentation -> parse trees via
the OpenNLP chunker engines), ``TreeVectorizer.java`` (parse, binarize,
collapse unaries, attach labels for RNTN training),
``HeadWordFinder.java`` (Collins-style head tables),
``BinarizeTreeTransformer.java`` (left-factored binarization, Manning
et al.), ``CollapseUnaries.java``, and the recursive-autoencoder ``Tree``
(``deeplearning4j-nn/.../recursive/Tree.java:32`` — label, children,
tokens, goldLabel, vector).

The reference's parser is a statistical model shipped as an OpenNLP binary
(JVM infrastructure, not capability); the analog is a deterministic
rule-based shallow constituency chunker over ``annotation.pos_tag``'s
universal-ish tagset, producing the same Tree structure, the same
transform pipeline, and the same vectorized output the RNTN-style
consumers need.  Phrase grammar (greedy, longest-match-first):

    NP   -> DET? ADJ* (NOUN|PRON|NUM)+
    PP   -> ADP NP
    ADJP -> ADV* ADJ+           (when not absorbed by an NP)
    VP   -> ADV* VERB+ ADV*
    S    -> (NP|VP|PP|ADJP|ADVP|X|PUNCT)+
"""

from __future__ import annotations

import dataclasses
from typing import Callable, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.nlp.annotation import (
    pos_tag, sentiment_score, split_sentences,
)

PHRASE_LABELS = ("NP", "VP", "PP", "ADJP", "ADVP", "X", "PUNCT")


@dataclasses.dataclass
class Tree:
    """≙ ``recursive/Tree.java:32``: label + children + covered tokens,
    with the RNTN-side fields (``vector``, ``gold_label``, ``value``)."""
    label: str
    children: List["Tree"] = dataclasses.field(default_factory=list)
    token: Optional[str] = None          # set on leaves only
    vector: Optional[np.ndarray] = None  # set by TreeVectorizer on leaves
    gold_label: Optional[str] = None
    value: float = 0.0                   # prediction slot (RNTN)

    def is_leaf(self) -> bool:
        return not self.children

    def is_preterminal(self) -> bool:
        return len(self.children) == 1 and self.children[0].is_leaf()

    def tokens(self) -> List[str]:
        if self.is_leaf():
            return [self.token] if self.token is not None else []
        out: List[str] = []
        for c in self.children:
            out.extend(c.tokens())
        return out

    def leaves(self) -> List["Tree"]:
        if self.is_leaf():
            return [self]
        out: List[Tree] = []
        for c in self.children:
            out.extend(c.leaves())
        return out

    def depth(self) -> int:
        if self.is_leaf():
            return 0
        return 1 + max(c.depth() for c in self.children)

    def set_gold_label_recursive(self, label: str) -> None:
        self.gold_label = label
        for c in self.children:
            c.set_gold_label_recursive(label)

    def __repr__(self) -> str:  # Penn-style bracketing, e.g. (NP (DET the))
        if self.is_leaf():
            return self.token or ""
        inner = " ".join(repr(c) for c in self.children)
        return f"({self.label} {inner})"


# ------------------------------------------------------------------ parser

def _chunk(tagged: Sequence) -> List[Tree]:
    """Greedy shallow parse of (token, tag) pairs into phrase subtrees."""
    def pre(i) -> Tree:  # preterminal: (TAG token)
        tok, tag = tagged[i]
        return Tree(tag, [Tree(tok, token=tok)])

    n = len(tagged)
    out: List[Tree] = []
    i = 0

    def tag(i):
        return tagged[i][1]

    def parse_np(i):
        """DET? ADJ* (NOUN|PRON|NUM)+ starting at i, or None."""
        j = i
        kids: List[Tree] = []
        if j < n and tag(j) == "DET":
            kids.append(pre(j))
            j += 1
        while j < n and tag(j) == "ADJ":
            kids.append(pre(j))
            j += 1
        heads = 0
        while j < n and tag(j) in ("NOUN", "PRON", "NUM"):
            kids.append(pre(j))
            j += 1
            heads += 1
        if heads == 0:
            return None, i
        return Tree("NP", kids), j

    while i < n:
        t = tag(i)
        if t == "ADP":  # PP -> ADP NP (falls back to bare ADP as X)
            np_tree, j = parse_np(i + 1)
            if np_tree is not None:
                out.append(Tree("PP", [pre(i), np_tree]))
                i = j
                continue
            out.append(Tree("X", [pre(i)]))
            i += 1
            continue
        np_tree, j = parse_np(i)
        if np_tree is not None:
            out.append(np_tree)
            i = j
            continue
        if t == "VERB":  # VP -> VERB+ ADV*
            kids = [pre(i)]
            i += 1
            while i < n and tag(i) in ("VERB", "ADV"):
                kids.append(pre(i))
                i += 1
            out.append(Tree("VP", kids))
            continue
        if t == "ADV":  # ADV* ADJ+ -> ADJP; ADV+ alone -> ADVP
            kids = [pre(i)]
            i += 1
            while i < n and tag(i) == "ADV":
                kids.append(pre(i))
                i += 1
            if i < n and tag(i) == "ADJ":
                while i < n and tag(i) == "ADJ":
                    kids.append(pre(i))
                    i += 1
                out.append(Tree("ADJP", kids))
            else:
                out.append(Tree("ADVP", kids))
            continue
        if t == "ADJ":
            kids = [pre(i)]
            i += 1
            while i < n and tag(i) == "ADJ":
                kids.append(pre(i))
                i += 1
            out.append(Tree("ADJP", kids))
            continue
        out.append(Tree("PUNCT" if t == "PUNCT" else "X", [pre(i)]))
        i += 1
    return out


class TreeParser:
    """Text -> one constituency ``Tree`` per sentence (≙
    ``TreeParser.getTrees(String)``: segment, tokenize, parse)."""

    def __init__(self, tokenizer_factory=None):
        if tokenizer_factory is None:
            from deeplearning4j_tpu.nlp.tokenization import (
                DefaultTokenizerFactory,
            )
            tokenizer_factory = DefaultTokenizerFactory()
        self.tokenizer_factory = tokenizer_factory

    def get_trees(self, text: str,
                  pre_processor: Optional[Callable[[str], str]] = None
                  ) -> List[Tree]:
        if not text:
            return []
        if pre_processor is not None:
            text = pre_processor(text)
        trees = []
        for sent in split_sentences(text):
            tokens = self.tokenizer_factory.create(sent).tokens()
            if not tokens:
                continue
            trees.append(Tree("S", _chunk(pos_tag(tokens))))
        return trees

    def get_trees_with_labels(self, text: str, labels: List[str]
                              ) -> List[Tree]:
        """≙ ``TreeParser.getTreesWithLabels``: one gold label per
        sentence, propagated to every node (RNTN training target)."""
        trees = self.get_trees(text)
        if len(labels) not in (1, len(trees)):
            raise ValueError(
                f"{len(labels)} labels for {len(trees)} sentences")
        for tree, label in zip(
                trees, labels * len(trees) if len(labels) == 1 else labels):
            tree.set_gold_label_recursive(label)
        return trees


# --------------------------------------------------------------- head words

class HeadWordFinder:
    """Collins-style head tables over the universal-ish tagset (≙
    ``HeadWordFinder.java``'s head1/head2 Penn tables): per phrase label,
    an ordered preference list and a search direction."""

    _RULES = {
        # label: (direction, [preferred child labels, most-preferred first])
        "NP": ("right", ["NOUN", "PRON", "NUM", "NP", "ADJ"]),
        "VP": ("left", ["VERB", "VP"]),
        "PP": ("left", ["ADP", "NP"]),
        "ADJP": ("right", ["ADJ", "ADV"]),
        "ADVP": ("right", ["ADV"]),
        "S": ("left", ["VP", "NP", "S"]),
    }

    def find_head(self, tree: Tree) -> Optional[Tree]:
        """The head PRETERMINAL of the subtree (None for empty/leaf)."""
        if tree.is_leaf():
            return None
        if tree.is_preterminal():
            return tree
        direction, prefs = self._RULES.get(
            tree.label.lstrip("@"), ("left", []))
        kids = (tree.children if direction == "left"
                else list(reversed(tree.children)))
        for want in prefs:
            for child in kids:
                if child.label.lstrip("@") == want:
                    return self.find_head(child)
        return self.find_head(kids[0])

    def find_head_word(self, tree: Tree) -> Optional[str]:
        head = self.find_head(tree)
        if head is None:
            return None
        toks = head.tokens()
        return toks[0] if toks else None


# --------------------------------------------------------------- transforms

class BinarizeTreeTransformer:
    """Left-factored binarization (≙ ``BinarizeTreeTransformer.java``,
    after Manning et al.): a node with > 2 children becomes a left-leaning
    chain of intermediate ``@Label`` nodes."""

    def transform(self, tree: Optional[Tree]) -> Optional[Tree]:
        if tree is None or tree.is_leaf():
            return tree
        kids = [self.transform(c) for c in tree.children]
        while len(kids) > 2:
            left = Tree(f"@{tree.label}", kids[:2],
                        gold_label=tree.gold_label)
            kids = [left] + kids[2:]
        return dataclasses.replace(tree, children=kids)


class CollapseUnaries:
    """Collapse unary chains X -> Y -> ... (≙ ``CollapseUnaries.java``),
    keeping the TOP label and never collapsing preterminals (the POS level
    stays, exactly like the reference's CNF step)."""

    def transform(self, tree: Optional[Tree]) -> Optional[Tree]:
        if tree is None or tree.is_leaf() or tree.is_preterminal():
            return tree
        node = tree
        while (len(node.children) == 1
               and not node.children[0].is_leaf()
               and not node.children[0].is_preterminal()):
            node = node.children[0]
        kids = [self.transform(c) for c in node.children]
        return dataclasses.replace(tree, children=kids)


# --------------------------------------------------------------- vectorizer

class TreeVectorizer:
    """Parse -> binarize -> collapse unaries (-> attach word vectors):
    ≙ ``TreeVectorizer.java`` ('vectorization of strings appropriate for
    an RNTN')."""

    def __init__(self, parser: Optional[TreeParser] = None,
                 tree_transformer=None, cnf_transformer=None):
        self.parser = parser or TreeParser()
        self.tree_transformer = tree_transformer or BinarizeTreeTransformer()
        self.cnf_transformer = cnf_transformer or CollapseUnaries()

    def get_trees(self, sentences: str) -> List[Tree]:
        out = []
        for t in self.parser.get_trees(sentences):
            out.append(self.cnf_transformer.transform(
                self.tree_transformer.transform(t)))
        return out

    def get_trees_with_labels(self, sentences: str,
                              labels: Optional[List[str]] = None
                              ) -> List[Tree]:
        """With explicit ``labels`` (one, or one per sentence) they are
        propagated like the reference's goldLabel; without, each sentence
        gets its lexicon sentiment sign (the reference's SentiWordNet-fed
        default corpus usage)."""
        if labels is not None:
            base = self.parser.get_trees_with_labels(sentences, labels)
        else:
            base = self.parser.get_trees(sentences)
            for t in base:
                s = sentiment_score(t.tokens())
                t.set_gold_label_recursive(
                    "positive" if s > 0 else "negative" if s < 0
                    else "neutral")
        return [self.cnf_transformer.transform(
            self.tree_transformer.transform(t)) for t in base]

    def vectorize(self, sentences: str, word_vectors,
                  labels: Optional[List[str]] = None) -> List[Tree]:
        """Attach ``word_vectors`` lookups (``WordVectors`` /
        ``SequenceVectors`` facade) at the leaves; OOV words get zeros,
        like the reference lookup table's default row."""
        trees = self.get_trees_with_labels(sentences, labels)
        dim = None
        for tree in trees:
            for leaf in tree.leaves():
                v = (word_vectors.get_word_vector(leaf.token.lower())
                     if leaf.token else None)
                if v is not None:
                    v = np.asarray(v, np.float32)
                    dim = len(v)
                leaf.vector = v
        if dim is not None:  # second pass: zeros for OOV, consistent dim
            for tree in trees:
                for leaf in tree.leaves():
                    if leaf.vector is None:
                        leaf.vector = np.zeros(dim, np.float32)
        return trees
