"""Bag-of-words / TF-IDF vectorizers.

Reference: ``bagofwords/vectorizer/{BagOfWordsVectorizer,TfidfVectorizer}
.java`` (710 LoC) — fit a vocab over documents, then transform each document
into a count / tf-idf row.
"""

from __future__ import annotations

import math
from typing import Iterable, List, Optional

import numpy as np

from deeplearning4j_tpu.nlp.tokenization import DefaultTokenizerFactory, TokenizerFactory
from deeplearning4j_tpu.nlp.vocab import Sequence, VocabCache, VocabConstructor, VocabWord


class BaseVectorizer:
    def __init__(self, tokenizer_factory: Optional[TokenizerFactory] = None,
                 min_word_frequency: int = 1,
                 stop_words: Iterable[str] = ()):
        self.tokenizer_factory = tokenizer_factory or DefaultTokenizerFactory()
        self.min_word_frequency = min_word_frequency
        self.stop_words = frozenset(stop_words)
        self.vocab: Optional[VocabCache] = None
        self.doc_count = 0
        self._doc_freq: Optional[np.ndarray] = None

    def _tokens(self, text: str) -> List[str]:
        return [t for t in self.tokenizer_factory.create(text).tokens()
                if t not in self.stop_words]

    def fit(self, documents: Iterable[str]) -> "BaseVectorizer":
        documents = list(documents)

        def seqs():
            for d in documents:
                seq = Sequence()
                for t in self._tokens(d):
                    seq.add_element(VocabWord(label=t))
                yield seq

        self.vocab = VocabConstructor(
            min_element_frequency=self.min_word_frequency).build_vocab(seqs())
        self.doc_count = len(documents)
        df = np.zeros(len(self.vocab), np.float64)
        for d in documents:
            seen = {self.vocab.index_of(t) for t in self._tokens(d)}
            for i in seen:
                if i >= 0:
                    df[i] += 1
        self._doc_freq = df
        return self

    def _counts(self, text: str) -> np.ndarray:
        row = np.zeros(len(self.vocab), np.float32)
        for t in self._tokens(text):
            i = self.vocab.index_of(t)
            if i >= 0:
                row[i] += 1.0
        return row

    def transform(self, document: str) -> np.ndarray:
        raise NotImplementedError

    def fit_transform(self, documents: Iterable[str]) -> np.ndarray:
        documents = list(documents)
        self.fit(documents)
        return np.stack([self.transform(d) for d in documents])

    def vocab_words(self) -> List[str]:
        return self.vocab.words()


class BagOfWordsVectorizer(BaseVectorizer):
    """Raw term counts. ≙ ``BagOfWordsVectorizer.java``."""

    def transform(self, document: str) -> np.ndarray:
        return self._counts(document)


class TfidfVectorizer(BaseVectorizer):
    """tf·idf with idf = log(N / df). ≙ ``TfidfVectorizer.java``."""

    def idf(self) -> np.ndarray:
        return np.log(np.maximum(self.doc_count, 1)
                      / np.maximum(self._doc_freq, 1.0)).astype(np.float32)

    def transform(self, document: str) -> np.ndarray:
        counts = self._counts(document)
        total = max(counts.sum(), 1.0)
        tf = counts / total
        return tf * self.idf()
