"""Moving windows over token streams.

Reference: ``deeplearning4j-nlp/.../text/movingwindow/Windows.java`` +
``Window.java`` (sliding, edge-padded context windows feeding window-based
models).  Padding uses the reference's <s>/</s> edge markers.
"""

from __future__ import annotations

import dataclasses
from typing import List

BEGIN = "<s>"
END = "</s>"


@dataclasses.dataclass
class Window:
    words: List[str]
    focus_index: int

    @property
    def focus_word(self) -> str:
        return self.words[self.focus_index]

    def as_list(self) -> List[str]:
        return list(self.words)


def windows(tokens: List[str], window_size: int = 5) -> List[Window]:
    """One Window per token, edge-padded so every window has exactly
    ``window_size`` words.  Odd sizes center the focus word; even sizes put
    it RIGHT of center (focus index ``window_size // 2``: e.g. size 4 gives
    2 words before, 1 after)."""
    if window_size < 1:
        raise ValueError("window_size must be >= 1")
    half = window_size // 2
    padded = [BEGIN] * half + list(tokens) + [END] * (window_size - half - 1)
    out = []
    for i in range(len(tokens)):
        out.append(Window(words=padded[i:i + window_size], focus_index=half))
    return out
