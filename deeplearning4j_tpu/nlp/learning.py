"""Batched embedding-update kernels: skip-gram / CBOW, negative sampling +
hierarchical softmax — the TPU re-derivation of the reference's Hogwild hot
loop.

Reference semantics: ``models/embeddings/learning/impl/elements/
SkipGram.java:124-194`` (iterateSample: input vector = syn0 row of the
*context* word, output path/samples of the *center* word; g = (label − σ)·lr;
accumulate neu1e into the input row) and ``CBOW.java`` (input = mean of
context rows).  The reference applies these one (center, context) pair at a
time across lock-free threads (``SequenceVectors.java:907``); that design is
TPU-hostile, so here a whole batch of pairs becomes ONE XLA program: gathers
→ einsum logits (MXU) → sigmoid grads → ``.at[].add`` scatter-accumulate.
Colliding rows inside a batch sum their updates deterministically — the
batched analogue of Hogwild's unsynchronised overlap, minus the racy reads.

All kernels are donated + jitted; the host only ships index arrays.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def _log_sigmoid(x):
    return -jnp.logaddexp(0.0, -x)


def _row_mean_scale(num_rows, idx, m, dtype):
    """1/multiplicity scale per occurrence, so colliding rows receive the
    MEAN of their pair-updates instead of the sum.  The reference's Hogwild
    interleaves collisions one-at-a-time; a batched sum of stale-value
    updates overshoots (and diverges on small vocabs), so the mean is the
    stable deterministic analogue."""
    counts = jnp.zeros((num_rows,), dtype).at[idx].add(m)
    return 1.0 / jnp.maximum(counts[idx], 1.0)


# ---------------------------------------------------------------------------
# negative-sampling kernels
# ---------------------------------------------------------------------------

@partial(jax.jit, donate_argnums=(0, 1))
def sg_ns_step(syn0, syn1neg, inputs, targets, negs, mask, lr):
    """One skip-gram negative-sampling batch.

    inputs  [B]    — syn0 rows to train (context words; DBOW: doc labels)
    targets [B]    — positive output words (window centers)
    negs    [B,K]  — sampled negative words
    mask    [B]    — 1.0 for real pairs, 0.0 padding
    """
    B, K = negs.shape
    D = syn0.shape[1]
    out_idx = jnp.concatenate([targets[:, None], negs], axis=1)      # [B,1+K]
    labels = jnp.concatenate(
        [jnp.ones((B, 1), syn0.dtype), jnp.zeros((B, K), syn0.dtype)], axis=1)
    h = syn0[inputs]                                                 # [B,D]
    w = syn1neg[out_idx]                                             # [B,1+K,D]
    logits = jnp.einsum("bd,bkd->bk", h, w)
    g = (labels - jax.nn.sigmoid(logits)) * lr * mask[:, None]       # [B,1+K]
    dh = jnp.einsum("bk,bkd->bd", g, w)                              # [B,D]
    dw = g[..., None] * h[:, None, :]                                # [B,1+K,D]
    in_scale = _row_mean_scale(syn0.shape[0], inputs, mask, syn0.dtype)
    flat_out = out_idx.reshape(-1)
    out_mask = jnp.broadcast_to(mask[:, None], out_idx.shape).reshape(-1)
    out_scale = _row_mean_scale(syn1neg.shape[0], flat_out, out_mask, syn0.dtype)
    syn0 = syn0.at[inputs].add(dh * in_scale[:, None])
    syn1neg = syn1neg.at[flat_out].add(dw.reshape(-1, D) * out_scale[:, None])
    loss = -(mask[:, None] * (labels * _log_sigmoid(logits)
                              + (1 - labels) * _log_sigmoid(-logits))).sum()
    return syn0, syn1neg, loss


@partial(jax.jit, donate_argnums=(0, 1))
def sg_hs_step(syn0, syn1, inputs, points, codes, code_mask, mask, lr):
    """One skip-gram hierarchical-softmax batch.

    points    [B,L] — inner-node rows (of the center word's Huffman path)
    codes     [B,L] — bit labels along the path (0/1)
    code_mask [B,L] — 1.0 within path length
    """
    D = syn0.shape[1]
    h = syn0[inputs]                                                 # [B,D]
    w = syn1[points]                                                 # [B,L,D]
    logits = jnp.einsum("bd,bld->bl", h, w)
    labels = 1.0 - codes                                             # word2vec convention
    m = code_mask * mask[:, None]
    g = (labels - jax.nn.sigmoid(logits)) * lr * m                   # [B,L]
    dh = jnp.einsum("bl,bld->bd", g, w)
    dw = g[..., None] * h[:, None, :]
    in_scale = _row_mean_scale(syn0.shape[0], inputs, mask, syn0.dtype)
    flat_pts = points.reshape(-1)
    pt_scale = _row_mean_scale(syn1.shape[0], flat_pts, m.reshape(-1), syn0.dtype)
    syn0 = syn0.at[inputs].add(dh * in_scale[:, None])
    syn1 = syn1.at[flat_pts].add(dw.reshape(-1, D) * pt_scale[:, None])
    loss = -(m * (labels * _log_sigmoid(logits)
                  + (1 - labels) * _log_sigmoid(-logits))).sum()
    return syn0, syn1, loss


# ---------------------------------------------------------------------------
# CBOW kernels (also Paragraph-Vectors DM when the label row is appended to
# the context group)
# ---------------------------------------------------------------------------

@partial(jax.jit, donate_argnums=(0, 1))
def cbow_ns_step(syn0, syn1neg, contexts, ctx_mask, targets, negs, mask, lr):
    """One CBOW negative-sampling batch.

    contexts [B,C] — context-word rows (−1-padded → masked by ctx_mask)
    ctx_mask [B,C] — 1.0 for real context members
    targets  [B]   — center words to predict
    """
    B, K = negs.shape
    D = syn0.shape[1]
    safe_ctx = jnp.maximum(contexts, 0)
    cvecs = syn0[safe_ctx] * ctx_mask[..., None]                     # [B,C,D]
    counts = jnp.maximum(ctx_mask.sum(-1, keepdims=True), 1.0)       # [B,1]
    h = cvecs.sum(1) / counts                                        # [B,D]
    out_idx = jnp.concatenate([targets[:, None], negs], axis=1)
    labels = jnp.concatenate(
        [jnp.ones((B, 1), syn0.dtype), jnp.zeros((B, K), syn0.dtype)], axis=1)
    w = syn1neg[out_idx]
    logits = jnp.einsum("bd,bkd->bk", h, w)
    g = (labels - jax.nn.sigmoid(logits)) * lr * mask[:, None]
    dh = jnp.einsum("bk,bkd->bd", g, w) / counts                     # split over members
    dw = g[..., None] * h[:, None, :]
    dctx = dh[:, None, :] * ctx_mask[..., None]                      # [B,C,D]
    flat_ctx = safe_ctx.reshape(-1)
    ctx_occ = (ctx_mask * mask[:, None]).reshape(-1)
    ctx_scale = _row_mean_scale(syn0.shape[0], flat_ctx, ctx_occ, syn0.dtype)
    flat_out = out_idx.reshape(-1)
    out_mask = jnp.broadcast_to(mask[:, None], out_idx.shape).reshape(-1)
    out_scale = _row_mean_scale(syn1neg.shape[0], flat_out, out_mask, syn0.dtype)
    syn0 = syn0.at[flat_ctx].add(dctx.reshape(-1, D) * ctx_scale[:, None])
    syn1neg = syn1neg.at[flat_out].add(dw.reshape(-1, D) * out_scale[:, None])
    loss = -(mask[:, None] * (labels * _log_sigmoid(logits)
                              + (1 - labels) * _log_sigmoid(-logits))).sum()
    return syn0, syn1neg, loss


@partial(jax.jit, donate_argnums=(0, 1))
def cbow_hs_step(syn0, syn1, contexts, ctx_mask, points, codes, code_mask, mask, lr):
    """One CBOW hierarchical-softmax batch."""
    D = syn0.shape[1]
    safe_ctx = jnp.maximum(contexts, 0)
    cvecs = syn0[safe_ctx] * ctx_mask[..., None]
    counts = jnp.maximum(ctx_mask.sum(-1, keepdims=True), 1.0)
    h = cvecs.sum(1) / counts
    w = syn1[points]
    logits = jnp.einsum("bd,bld->bl", h, w)
    labels = 1.0 - codes
    m = code_mask * mask[:, None]
    g = (labels - jax.nn.sigmoid(logits)) * lr * m
    dh = jnp.einsum("bl,bld->bd", g, w) / counts
    dw = g[..., None] * h[:, None, :]
    dctx = dh[:, None, :] * ctx_mask[..., None]
    flat_ctx = safe_ctx.reshape(-1)
    ctx_occ = (ctx_mask * mask[:, None]).reshape(-1)
    ctx_scale = _row_mean_scale(syn0.shape[0], flat_ctx, ctx_occ, syn0.dtype)
    flat_pts = points.reshape(-1)
    pt_scale = _row_mean_scale(syn1.shape[0], flat_pts, m.reshape(-1), syn0.dtype)
    syn0 = syn0.at[flat_ctx].add(dctx.reshape(-1, D) * ctx_scale[:, None])
    syn1 = syn1.at[flat_pts].add(dw.reshape(-1, D) * pt_scale[:, None])
    loss = -(m * (labels * _log_sigmoid(logits)
                  + (1 - labels) * _log_sigmoid(-logits))).sum()
    return syn0, syn1, loss


# ---------------------------------------------------------------------------
# GloVe kernel (weighted least squares + AdaGrad)
# ---------------------------------------------------------------------------

@partial(jax.jit, donate_argnums=(0, 1, 2, 3, 4, 5, 6, 7))
def glove_step(w, wc, b, bc, hw, hwc, hb, hbc, rows, cols, xij, mask, lr,
               x_max, alpha):
    """One GloVe batch: minimise f(X)(wᵢ·w̃ⱼ + bᵢ + b̃ⱼ − log Xᵢⱼ)² with
    per-coordinate AdaGrad.  ≙ ``learning/impl/elements/GloVe.java``
    (iterateSample) re-batched.

    w/wc   [V,D] main/context embeddings, b/bc [V] biases,
    h*      AdaGrad squared-grad accumulators.
    """
    wi = w[rows]
    wj = wc[cols]
    diff = (jnp.einsum("bd,bd->b", wi, wj) + b[rows] + bc[cols]
            - jnp.log(jnp.maximum(xij, 1e-12)))
    f = jnp.minimum((xij / x_max) ** alpha, 1.0) * mask
    g = f * diff                                                     # [B]
    gw = g[:, None] * wj
    gwc = g[:, None] * wi
    eps = 1e-8

    def ada(hist, idx, grad):
        hist = hist.at[idx].add(grad * grad)
        scale = lr / jnp.sqrt(hist[idx] + eps)
        return hist, scale * grad

    hw, step_w = ada(hw, rows, gw)
    hwc, step_wc = ada(hwc, cols, gwc)
    hb, step_b = ada(hb, rows, g)
    hbc, step_bc = ada(hbc, cols, g)
    w = w.at[rows].add(-step_w)
    wc = wc.at[cols].add(-step_wc)
    b = b.at[rows].add(-step_b)
    bc = bc.at[cols].add(-step_bc)
    loss = 0.5 * (f * diff * diff).sum()
    return w, wc, b, bc, hw, hwc, hb, hbc, loss
