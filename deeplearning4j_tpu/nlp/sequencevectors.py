"""SequenceVectors — the generic embedding-training engine.

Reference: ``models/sequencevectors/SequenceVectors.java:148-235`` (fit:
build vocab → Huffman → resetWeights → epochs of multithreaded Hogwild
training with per-thread linear lr annealing) and its Builder (:735).

TPU redesign: the AsyncSequencer + N VectorCalculationsThreads producer/
consumer Hogwild architecture is replaced by a *batched pair pipeline*:

  host: sequences → index arrays → (vectorised) window-pair extraction →
        fixed-size batches (padded, masked)
  device: ONE jitted kernel per batch (``nlp/learning.py``) — gather,
        einsum on the MXU, scatter-add — deterministic given the seed.

The linear lr anneal over total processed words is preserved
(``SequenceVectors.java`` per-thread alpha math), as are subsampling,
reduced windows, and the SkipGram/CBOW + HS/NS algorithm matrix.

Generic over element streams: Word2Vec feeds tokenised sentences, DeepWalk
feeds vertex walks, ParagraphVectors feeds labelled documents (labels become
special vocab elements trained by DBOW/DM — ``impl/sequence/{DBOW,DM}``).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Callable, Iterable, List, Optional, Sequence as Seq, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp import learning
from deeplearning4j_tpu.nlp.lookup import InMemoryLookupTable
from deeplearning4j_tpu.nlp.vocab import (
    Sequence,
    SequenceElement,
    VocabCache,
    VocabConstructor,
    build_huffman,
    codes_matrix,
)
from deeplearning4j_tpu.nlp.wordvectors import WordVectors


@dataclass
class VectorsConfiguration:
    """≙ the reference Builder knobs (``SequenceVectors.Builder`` :735)."""

    layer_size: int = 100
    window: int = 5
    negative: int = 0                  # K negative samples; 0 = off
    use_hierarchic_softmax: bool = True
    min_word_frequency: int = 1
    epochs: int = 1
    iterations: int = 1                # passes over each batch
    learning_rate: float = 0.025
    min_learning_rate: float = 1e-4
    subsampling: float = 0.0           # e.g. 1e-3; 0 = off
    seed: int = 12345
    batch_size: int = 512
    elements_algorithm: str = "skipgram"   # skipgram | cbow
    sequence_algorithm: str = "dbow"       # dbow | dm (PV only)
    train_elements: bool = True
    train_sequences: bool = False      # PV: train label vectors
    use_adagrad: bool = False


class SequenceVectors(WordVectors):
    def __init__(self, config: VectorsConfiguration,
                 sequence_provider: Callable[[], Iterable[Sequence]]):
        """``sequence_provider`` returns a fresh iterable per epoch
        (≙ iterator reset semantics)."""
        self.config = config
        self.sequence_provider = sequence_provider
        self.vocab: Optional[VocabCache] = None
        self.lookup: Optional[InMemoryLookupTable] = None
        self._rs = np.random.RandomState(config.seed)
        self._key = jax.random.PRNGKey(config.seed)
        self._codes = self._points = self._code_lengths = None
        self.cum_loss: float = 0.0

    # ------------------------------------------------------------------ fit
    def fit(self) -> "SequenceVectors":
        cfg = self.config
        if self.vocab is None:
            self.vocab = VocabConstructor(
                min_element_frequency=cfg.min_word_frequency
            ).build_vocab(self.sequence_provider())
        if cfg.use_hierarchic_softmax:
            build_huffman(self.vocab)
            self._codes, self._points, self._code_lengths = codes_matrix(self.vocab)
        self.lookup = InMemoryLookupTable(
            self.vocab, cfg.layer_size, seed=cfg.seed,
            negative=cfg.negative, use_hs=cfg.use_hierarchic_softmax,
            use_adagrad=cfg.use_adagrad)
        self.lookup.reset_weights()

        total_words = self.vocab.total_word_count * max(cfg.epochs, 1)
        processed = 0
        for _ in range(cfg.epochs):
            for batch in self._batches():
                lr = max(cfg.min_learning_rate,
                         cfg.learning_rate * (1.0 - processed / max(total_words, 1.0)))
                for _ in range(cfg.iterations):
                    self._apply_batch(batch, lr)
                processed += batch["n_words"]
        return self

    # ------------------------------------------------- pair/batch generation
    def _sequence_indices(self, seq: Sequence) -> Tuple[np.ndarray, Optional[int]]:
        idx = [self.vocab.index_of(el.label if isinstance(el, SequenceElement)
                                   else str(el))
               for el in seq.elements]
        idx = np.array([i for i in idx if i >= 0], np.int32)
        cfg = self.config
        if cfg.subsampling > 0 and len(idx):
            freqs = np.array(
                [self.vocab.element_at_index(i).element_frequency for i in idx],
                np.float64)
            ran = (np.sqrt(freqs / (cfg.subsampling * self.vocab.total_word_count)) + 1) \
                * (cfg.subsampling * self.vocab.total_word_count) / np.maximum(freqs, 1e-12)
            idx = idx[self._rs.rand(len(idx)) < ran]
        label_idx = None
        if seq.sequence_label is not None:
            li = self.vocab.index_of(seq.sequence_label.label)
            label_idx = li if li >= 0 else None
        return idx, label_idx

    def _window_pairs(self, idx: np.ndarray):
        """Skip-gram pairs (input=context row, target=center) with reduced
        windows — vectorised per shift distance."""
        n = len(idx)
        if n < 2:
            return np.empty((0, 2), np.int32), np.empty((0,), np.int32)
        b = self._rs.randint(1, self.config.window + 1, size=n)
        inputs, targets, centers_pos = [], [], []
        for s in range(1, self.config.window + 1):
            m = b >= s
            # context at center-s (center index i >= s)
            sel = np.nonzero(m[s:])[0] + s
            inputs.append(idx[sel - s]); targets.append(idx[sel]); centers_pos.append(sel)
            # context at center+s
            sel2 = np.nonzero(m[:n - s])[0]
            inputs.append(idx[sel2 + s]); targets.append(idx[sel2]); centers_pos.append(sel2)
        return (np.stack([np.concatenate(inputs), np.concatenate(targets)], 1),
                np.concatenate(centers_pos))

    def _context_groups(self, idx: np.ndarray):
        """CBOW groups: per center, the (−1-padded) context window."""
        n = len(idx)
        C = 2 * self.config.window
        if n < 2:
            return (np.empty((0, C), np.int32), np.empty((0,), np.int32))
        b = self._rs.randint(1, self.config.window + 1, size=n)
        ctx = np.full((n, C), -1, np.int32)
        for i in range(n):
            lo, hi = max(0, i - b[i]), min(n, i + b[i] + 1)
            members = np.concatenate([idx[lo:i], idx[i + 1:hi]])
            ctx[i, :len(members)] = members
        return ctx, idx

    def _batches(self):
        """Assemble fixed-size training batches from the sequence stream."""
        cfg = self.config
        algo = cfg.elements_algorithm
        buf_inputs: List[np.ndarray] = []
        buf_targets: List[np.ndarray] = []
        buf_ctx: List[np.ndarray] = []
        count = 0
        n_words = 0

        def flush():
            nonlocal buf_inputs, buf_targets, buf_ctx, count, n_words
            if count == 0:
                return None
            if algo == "skipgram" or not cfg.train_elements:
                inputs = np.concatenate(buf_inputs) if buf_inputs else np.empty(0, np.int32)
                targets = np.concatenate(buf_targets) if buf_targets else np.empty(0, np.int32)
                batch = {"kind": "sg", "inputs": inputs, "targets": targets,
                         "n_words": n_words}
            else:
                ctx = np.concatenate(buf_ctx) if buf_ctx else np.empty((0, 2 * cfg.window), np.int32)
                targets = np.concatenate(buf_targets) if buf_targets else np.empty(0, np.int32)
                batch = {"kind": "cbow", "contexts": ctx, "targets": targets,
                         "n_words": n_words}
            buf_inputs, buf_targets, buf_ctx = [], [], []
            count = 0
            n_words = 0
            return batch

        for seq in self.sequence_provider():
            idx, label_idx = self._sequence_indices(seq)
            n_words += len(idx)
            if cfg.train_elements:
                if algo == "skipgram":
                    pairs, _ = self._window_pairs(idx)
                    if len(pairs):
                        if cfg.train_sequences and label_idx is not None \
                                and cfg.sequence_algorithm == "dm":
                            pass  # DM handled via context groups below
                        buf_inputs.append(pairs[:, 0])
                        buf_targets.append(pairs[:, 1])
                        count += len(pairs)
                else:  # cbow
                    ctx, centers = self._context_groups(idx)
                    if cfg.train_sequences and label_idx is not None and len(centers):
                        ctx = np.concatenate(
                            [ctx, np.full((len(ctx), 1), label_idx, np.int32)], 1)
                    if len(centers):
                        buf_ctx.append(ctx)
                        buf_targets.append(centers)
                        count += len(centers)
            if cfg.train_sequences and label_idx is not None:
                if cfg.sequence_algorithm == "dbow" or not cfg.train_elements:
                    # DBOW: label row predicts every word of the sequence
                    if len(idx):
                        buf_inputs.append(np.full(len(idx), label_idx, np.int32))
                        buf_targets.append(idx)
                        count += len(idx)
                elif cfg.sequence_algorithm == "dm" and algo == "skipgram":
                    # DM with skip-gram elements: label also predicts words
                    if len(idx):
                        buf_inputs.append(np.full(len(idx), label_idx, np.int32))
                        buf_targets.append(idx)
                        count += len(idx)
            if count >= cfg.batch_size:
                yield flush()
        tail = flush()
        if tail is not None:
            yield tail

    # --------------------------------------------------------- batch apply
    def _next_key(self):
        self._key, sub = jax.random.split(self._key)
        return sub

    def _pad(self, arr: np.ndarray, B: int, fill=0):
        pad = B - len(arr)
        if pad <= 0:
            return arr
        pad_block = np.full((pad,) + arr.shape[1:], fill, arr.dtype)
        return np.concatenate([arr, pad_block], 0)

    def _apply_batch(self, batch, lr: float) -> None:
        cfg = self.config
        lk = self.lookup
        n = len(batch["targets"])
        if n == 0:
            return
        # pad to the fixed batch size so XLA compiles one program shape
        B = max(cfg.batch_size, int(2 ** math.ceil(math.log2(max(n, 1)))))
        mask = self._pad(np.ones(n, np.float32), B)
        targets = jnp.asarray(self._pad(batch["targets"], B))
        lr = jnp.float32(lr)
        if batch["kind"] == "sg":
            inputs = jnp.asarray(self._pad(batch["inputs"], B))
            if cfg.negative > 0:
                negs = lk.sample_negatives(self._next_key(), (B, cfg.negative))
                lk.syn0, lk.syn1neg, loss = learning.sg_ns_step(
                    lk.syn0, lk.syn1neg, inputs, targets, negs,
                    jnp.asarray(mask), lr)
                self.cum_loss += float(loss)
            if cfg.use_hierarchic_softmax:
                pts = jnp.asarray(self._points)[targets]
                cds = jnp.asarray(self._codes)[targets]
                ln = jnp.asarray(self._code_lengths)[targets]
                code_mask = (jnp.arange(self._codes.shape[1])[None, :]
                             < ln[:, None]).astype(jnp.float32)
                lk.syn0, lk.syn1, loss = learning.sg_hs_step(
                    lk.syn0, lk.syn1, inputs, pts, cds, code_mask,
                    jnp.asarray(mask), lr)
                self.cum_loss += float(loss)
        else:  # cbow
            C = batch["contexts"].shape[1] if len(batch["contexts"]) else 2 * cfg.window
            ctx = jnp.asarray(self._pad(batch["contexts"], B, fill=-1))
            ctx_mask = (ctx >= 0).astype(jnp.float32)
            if cfg.negative > 0:
                negs = lk.sample_negatives(self._next_key(), (B, cfg.negative))
                lk.syn0, lk.syn1neg, loss = learning.cbow_ns_step(
                    lk.syn0, lk.syn1neg, ctx, ctx_mask, targets, negs,
                    jnp.asarray(mask), lr)
                self.cum_loss += float(loss)
            if cfg.use_hierarchic_softmax:
                pts = jnp.asarray(self._points)[targets]
                cds = jnp.asarray(self._codes)[targets]
                ln = jnp.asarray(self._code_lengths)[targets]
                code_mask = (jnp.arange(self._codes.shape[1])[None, :]
                             < ln[:, None]).astype(jnp.float32)
                lk.syn0, lk.syn1, loss = learning.cbow_hs_step(
                    lk.syn0, lk.syn1, ctx, ctx_mask, pts, cds, code_mask,
                    jnp.asarray(mask), lr)
                self.cum_loss += float(loss)

    # ------------------------------------------------- WordVectors surface
    @property
    def syn0(self):
        return self.lookup.syn0

    def vocab_cache(self) -> VocabCache:
        return self.vocab
