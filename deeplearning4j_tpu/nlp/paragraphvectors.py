"""ParagraphVectors (doc2vec): label-aware documents over SequenceVectors.

Reference: ``models/paragraphvectors/ParagraphVectors.java`` — labels are
special vocab elements trained alongside words via DBOW
(``learning/impl/sequence/DBOW.java``: label row predicts each word) or DM
(``DM.java``: label joins the averaged context), ``inferVector`` (train a
fresh vector against frozen weights), ``predict`` (nearest label).
"""

from __future__ import annotations

from typing import Iterable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp import learning
from deeplearning4j_tpu.nlp.documents import (
    LabelAwareIterator,
    LabelledDocument,
    LabelsSource,
    SimpleLabelAwareIterator,
)
from deeplearning4j_tpu.nlp.sequencevectors import SequenceVectors, VectorsConfiguration
from deeplearning4j_tpu.nlp.tokenization import DefaultTokenizerFactory, TokenizerFactory
from deeplearning4j_tpu.nlp.vocab import Sequence, VocabWord


class ParagraphVectors(SequenceVectors):
    def __init__(self, config: VectorsConfiguration,
                 document_iterator: LabelAwareIterator,
                 tokenizer_factory: TokenizerFactory):
        self.document_iterator = document_iterator
        self.tokenizer_factory = tokenizer_factory
        self.labels_source = LabelsSource()
        config.train_sequences = True
        super().__init__(config, self._sequences)

    def _sequences(self) -> Iterable[Sequence]:
        self.document_iterator.reset()
        while self.document_iterator.has_next():
            doc = self.document_iterator.next_document()
            tokens = self.tokenizer_factory.create(doc.content).tokens()
            if not tokens:
                continue
            seq = Sequence()
            for t in tokens:
                seq.add_element(VocabWord(label=t))
            label = doc.label or self.labels_source.next_label()
            self.labels_source.store_label(label)
            seq.set_sequence_label(VocabWord(label=label, special=True))
            yield seq

    # ----------------------------------------------------------- inference
    def infer_vector(self, text: str, steps: int = 30,
                     learning_rate: float = 0.025) -> np.ndarray:
        """Train a fresh doc vector against frozen word weights.
        ≙ ``ParagraphVectors.inferVector``."""
        cfg = self.config
        tokens = self.tokenizer_factory.create(text).tokens()
        idx = np.array([self.vocab.index_of(t) for t in tokens], np.int64)
        idx = idx[idx >= 0].astype(np.int32)
        D = cfg.layer_size
        rs = np.random.RandomState(cfg.seed)
        vec = jnp.asarray((rs.rand(1, D).astype(np.float32) - 0.5) / D)
        if len(idx) == 0:
            return np.asarray(vec[0])
        lk = self.lookup
        n = len(idx)
        inputs = jnp.zeros((n,), jnp.int32)  # every pair trains row 0 of `vec`
        targets = jnp.asarray(idx)
        mask = jnp.ones((n,), jnp.float32)
        for step in range(steps):
            lr = jnp.float32(learning_rate * (1.0 - step / steps) + 1e-4)
            if cfg.negative > 0:
                negs = lk.sample_negatives(self._next_key(), (n, cfg.negative))
                # frozen output weights: discard their update by passing a
                # copy and keeping only the doc-vector row
                vec, _, _ = learning.sg_ns_step(
                    vec, lk.syn1neg + 0, inputs, targets, negs, mask, lr)
            if cfg.use_hierarchic_softmax:
                pts = jnp.asarray(self._points)[targets]
                cds = jnp.asarray(self._codes)[targets]
                ln = jnp.asarray(self._code_lengths)[targets]
                code_mask = (jnp.arange(self._codes.shape[1])[None, :]
                             < ln[:, None]).astype(jnp.float32)
                vec, _, _ = learning.sg_hs_step(
                    vec, lk.syn1 + 0, inputs, pts, cds, code_mask, mask, lr)
        return np.asarray(vec[0])

    def predict(self, text: str) -> Optional[str]:
        """Nearest label to the inferred document vector.
        ≙ ``ParagraphVectors.predict``."""
        v = self.infer_vector(text)
        labels = [l for l in self.labels_source.labels
                  if self.vocab.contains_word(l)]
        if not labels:
            return None
        mat = self.get_word_vector_matrix(labels)
        mat = mat / np.maximum(np.linalg.norm(mat, axis=1, keepdims=True), 1e-12)
        qn = v / max(np.linalg.norm(v), 1e-12)
        return labels[int(np.argmax(mat @ qn))]

    class Builder:
        """≙ ``ParagraphVectors.Builder``."""

        def __init__(self):
            self._cfg = VectorsConfiguration(train_sequences=True)
            self._iterator: Optional[LabelAwareIterator] = None
            self._tokenizer: TokenizerFactory = DefaultTokenizerFactory()

        def iterate(self, iterator) -> "ParagraphVectors.Builder":
            if isinstance(iterator, (list, tuple)):
                iterator = SimpleLabelAwareIterator(iterator)
            self._iterator = iterator
            return self

        def tokenizer_factory(self, tf):
            self._tokenizer = tf
            return self

        def layer_size(self, n: int):
            self._cfg.layer_size = n
            return self

        def window_size(self, n: int):
            self._cfg.window = n
            return self

        def min_word_frequency(self, n: int):
            self._cfg.min_word_frequency = n
            return self

        def negative_sample(self, n: int):
            self._cfg.negative = int(n)
            return self

        def use_hierarchic_softmax(self, b: bool):
            self._cfg.use_hierarchic_softmax = b
            return self

        def learning_rate(self, lr: float):
            self._cfg.learning_rate = lr
            return self

        def epochs(self, n: int):
            self._cfg.epochs = n
            return self

        def seed(self, s: int):
            self._cfg.seed = s
            return self

        def batch_size(self, n: int):
            self._cfg.batch_size = n
            return self

        def sequence_learning_algorithm(self, name: str):
            self._cfg.sequence_algorithm = name.lower()
            return self

        def train_words_representation(self, b: bool):
            self._cfg.train_elements = b
            return self

        def build(self) -> "ParagraphVectors":
            if self._iterator is None:
                raise ValueError("ParagraphVectors.Builder: iterate(...) required")
            return ParagraphVectors(self._cfg, self._iterator, self._tokenizer)
