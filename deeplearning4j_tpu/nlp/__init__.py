"""NLP embeddings stack (≙ deeplearning4j-nlp-parent).

TPU-first redesign of the reference's Hogwild embedding trainer: batched
jitted gather/einsum/scatter kernels over device-resident embedding matrices
(see ``nlp/learning.py``), one generic SequenceVectors engine, and the
Word2Vec / ParagraphVectors / GloVe facades on top.
"""

from deeplearning4j_tpu.nlp.bow import BagOfWordsVectorizer, TfidfVectorizer
from deeplearning4j_tpu.nlp.documents import (
    AggregatingSentenceIterator,
    BasicLineIterator,
    CollectionSentenceIterator,
    FileSentenceIterator,
    LabelAwareIterator,
    LabelledDocument,
    LabelsSource,
    SentenceIterator,
    SimpleLabelAwareIterator,
)
from deeplearning4j_tpu.nlp.glove import CoOccurrences, Glove
from deeplearning4j_tpu.nlp.lookup import InMemoryLookupTable
from deeplearning4j_tpu.nlp.paragraphvectors import ParagraphVectors
from deeplearning4j_tpu.nlp.sequencevectors import (
    SequenceVectors,
    VectorsConfiguration,
)
from deeplearning4j_tpu.nlp.tokenization import (
    STOP_WORDS,
    CommonPreprocessor,
    DefaultTokenizerFactory,
    EndingPreProcessor,
    NGramTokenizerFactory,
    TokenizerFactory,
)
from deeplearning4j_tpu.nlp.vocab import (
    Sequence,
    SequenceElement,
    VocabCache,
    VocabConstructor,
    VocabWord,
    build_huffman,
    codes_matrix,
)
from deeplearning4j_tpu.nlp.word2vec import StaticWord2Vec, Word2Vec
from deeplearning4j_tpu.nlp.wordvectors import WordVectors
from deeplearning4j_tpu.nlp import serializer as WordVectorSerializer
from deeplearning4j_tpu.nlp.stopwords import (
    StopWordsRemover, get_stop_words, is_stop_word, remove_stop_words,
)
from deeplearning4j_tpu.nlp.annotation import (
    TextAnnotator, pos_tag, sentiment_score, split_sentences,
)
from deeplearning4j_tpu.nlp.treeparser import (
    BinarizeTreeTransformer, CollapseUnaries, HeadWordFinder, Tree,
    TreeParser, TreeVectorizer,
)
from deeplearning4j_tpu.nlp.windows import Window, windows

__all__ = [
    "BagOfWordsVectorizer", "TfidfVectorizer", "AggregatingSentenceIterator",
    "BasicLineIterator", "CollectionSentenceIterator", "FileSentenceIterator",
    "LabelAwareIterator", "LabelledDocument", "LabelsSource",
    "SentenceIterator", "SimpleLabelAwareIterator", "CoOccurrences", "Glove",
    "InMemoryLookupTable", "ParagraphVectors", "SequenceVectors",
    "VectorsConfiguration", "STOP_WORDS", "CommonPreprocessor",
    "DefaultTokenizerFactory", "EndingPreProcessor", "NGramTokenizerFactory",
    "TokenizerFactory", "Sequence", "SequenceElement", "VocabCache",
    "VocabConstructor", "VocabWord", "build_huffman", "codes_matrix",
    "StaticWord2Vec", "Word2Vec", "WordVectors", "WordVectorSerializer",
    "StopWordsRemover", "get_stop_words", "is_stop_word",
    "remove_stop_words", "TextAnnotator", "pos_tag", "sentiment_score",
    "split_sentences", "BinarizeTreeTransformer", "CollapseUnaries",
    "HeadWordFinder", "Tree", "TreeParser", "TreeVectorizer",
    "Window", "windows",
]
