"""WordVectors query surface: similarity / wordsNearest / arithmetic.

Reference: ``models/embeddings/wordvectors/WordVectors.java`` +
``models/embeddings/reader/impl/BasicModelUtils.java`` (cosine
``wordsNearest``, ``wordsNearestSum``, similarity).

TPU redesign: nearest-neighbour queries are one normalised matmul + top-k on
device (``jax.lax.top_k``) instead of the reference's per-row host loops.
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class WordVectors:
    """Mixin over ``self.lookup`` (InMemoryLookupTable) + ``self.vocab``."""

    # subclasses provide: self.lookup, self.vocab

    def has_word(self, word: str) -> bool:
        return self.vocab.contains_word(word)

    def get_word_vector(self, word: str) -> Optional[np.ndarray]:
        return self.lookup.vector(word)

    def get_word_vector_matrix(self, words: Sequence[str]) -> np.ndarray:
        idx = [self.vocab.index_of(w) for w in words]
        if any(i < 0 for i in idx):
            missing = [w for w, i in zip(words, idx) if i < 0]
            raise KeyError(f"Words not in vocab: {missing}")
        return np.asarray(self.lookup.syn0[jnp.asarray(idx)])

    def similarity(self, a: str, b: str) -> float:
        va, vb = self.get_word_vector(a), self.get_word_vector(b)
        if va is None or vb is None:
            return float("nan")
        denom = (np.linalg.norm(va) * np.linalg.norm(vb))
        if denom == 0:
            return 0.0
        return float(np.dot(va, vb) / denom)

    def _normed_syn0(self) -> jax.Array:
        syn0 = self.lookup.syn0
        return syn0 / jnp.maximum(jnp.linalg.norm(syn0, axis=1, keepdims=True), 1e-12)

    def words_nearest(self, positive, negative=(), top_n: int = 10) -> List[str]:
        """Cosine nearest words to (Σ positive − Σ negative); query words are
        excluded from the result (reference BasicModelUtils semantics).
        ``positive`` may be a single word, a list of words, or a raw vector."""
        exclude = set()
        if isinstance(positive, str):
            positive = [positive]
        if isinstance(positive, (list, tuple)) and positive and isinstance(positive[0], str):
            vecs = [self.get_word_vector(w) for w in positive]
            exclude.update(positive)
            if any(v is None for v in vecs):
                return []
            query = np.sum(vecs, axis=0)
        else:
            query = np.asarray(positive)
        for w in (negative if not isinstance(negative, str) else [negative]):
            v = self.get_word_vector(w)
            exclude.add(w)
            if v is not None:
                query = query - v
        qn = query / max(np.linalg.norm(query), 1e-12)
        sims = self._normed_syn0() @ jnp.asarray(qn, jnp.float32)
        k = min(top_n + len(exclude), int(sims.shape[0]))
        _, top_idx = jax.lax.top_k(sims, k)
        out = []
        for i in np.asarray(top_idx):
            label = self.vocab.element_at_index(int(i)).label
            if label in exclude:
                continue
            out.append(label)
            if len(out) == top_n:
                break
        return out

    def words_nearest_sum(self, positive, negative=(), top_n: int = 10) -> List[str]:
        return self.words_nearest(positive, negative, top_n)

    def similar_words_in_vocab_to(self, word: str, accuracy: float) -> List[str]:
        v = self.get_word_vector(word)
        if v is None:
            return []
        qn = v / max(np.linalg.norm(v), 1e-12)
        sims = np.asarray(self._normed_syn0() @ jnp.asarray(qn, jnp.float32))
        out = [self.vocab.element_at_index(i).label
               for i in np.nonzero(sims >= accuracy)[0]]
        return [w for w in out if w != word]
