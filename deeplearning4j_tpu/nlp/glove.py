"""GloVe: co-occurrence counting + weighted-least-squares embedding fit.

Reference: ``models/glove/Glove.java``, ``models/glove/AbstractCoOccurrences
.java`` (streaming window-weighted co-occurrence counts; 1/d weighting),
``models/embeddings/learning/impl/elements/GloVe.java`` (per-pair AdaGrad
update, xMax=100, alpha=0.75).

TPU redesign: co-occurrence counting is a host-side dict pass (the spill-file
machinery of the reference is an out-of-core detail, not a capability); the
optimisation loop ships shuffled (row, col, Xij) batches to the jitted
``glove_step`` kernel (``nlp/learning.py``) — AdaGrad scatter updates on
device.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Dict, Iterable, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp import learning
from deeplearning4j_tpu.nlp.documents import CollectionSentenceIterator, SentenceIterator
from deeplearning4j_tpu.nlp.lookup import InMemoryLookupTable
from deeplearning4j_tpu.nlp.tokenization import DefaultTokenizerFactory, TokenizerFactory
from deeplearning4j_tpu.nlp.vocab import (
    Sequence,
    VocabCache,
    VocabConstructor,
    VocabWord,
)
from deeplearning4j_tpu.nlp.wordvectors import WordVectors


class CoOccurrences:
    """Symmetric window-weighted co-occurrence counts (weight 1/distance).
    ≙ ``AbstractCoOccurrences.java``."""

    def __init__(self, vocab: VocabCache, window: int = 15,
                 symmetric: bool = True):
        self.vocab = vocab
        self.window = window
        self.symmetric = symmetric
        self.counts: Dict[Tuple[int, int], float] = defaultdict(float)

    def fit_sentences(self, token_lists: Iterable[list]) -> "CoOccurrences":
        for tokens in token_lists:
            idx = [self.vocab.index_of(t) for t in tokens]
            idx = [i for i in idx if i >= 0]
            n = len(idx)
            for i in range(n):
                for d in range(1, self.window + 1):
                    j = i + d
                    if j >= n:
                        break
                    w = 1.0 / d
                    self.counts[(idx[i], idx[j])] += w
                    if self.symmetric:
                        self.counts[(idx[j], idx[i])] += w
        return self

    def as_arrays(self):
        if not self.counts:
            return (np.empty(0, np.int32), np.empty(0, np.int32),
                    np.empty(0, np.float32))
        items = list(self.counts.items())
        rows = np.array([k[0] for k, _ in items], np.int32)
        cols = np.array([k[1] for k, _ in items], np.int32)
        vals = np.array([v for _, v in items], np.float32)
        return rows, cols, vals


class Glove(WordVectors):
    def __init__(self, config=None, sentence_iterator: SentenceIterator = None,
                 tokenizer_factory: TokenizerFactory = None,
                 layer_size: int = 100, window: int = 15, epochs: int = 5,
                 learning_rate: float = 0.05, x_max: float = 100.0,
                 alpha: float = 0.75, min_word_frequency: int = 1,
                 batch_size: int = 1024, seed: int = 12345,
                 symmetric: bool = True):
        self.sentence_iterator = sentence_iterator
        self.tokenizer_factory = tokenizer_factory or DefaultTokenizerFactory()
        self.layer_size = layer_size
        self.window = window
        self.epochs = epochs
        self.learning_rate = learning_rate
        self.x_max = x_max
        self.alpha = alpha
        self.min_word_frequency = min_word_frequency
        self.batch_size = batch_size
        self.seed = seed
        self.symmetric = symmetric
        self.vocab: Optional[VocabCache] = None
        self.lookup: Optional[InMemoryLookupTable] = None
        self.cum_loss = 0.0

    def _token_lists(self):
        self.sentence_iterator.reset()
        while self.sentence_iterator.has_next():
            s = self.sentence_iterator.next_sentence()
            if s:
                toks = self.tokenizer_factory.create(s).tokens()
                if toks:
                    yield toks

    # seam for the distributed variant (DistributedGlove shards this)
    _glove_step = staticmethod(learning.glove_step)

    def fit(self) -> "Glove":
        # vocab
        def seqs():
            for toks in self._token_lists():
                seq = Sequence()
                for t in toks:
                    seq.add_element(VocabWord(label=t))
                yield seq

        self.vocab = VocabConstructor(
            min_element_frequency=self.min_word_frequency).build_vocab(seqs())
        V, D = len(self.vocab), self.layer_size
        cooc = CoOccurrences(self.vocab, self.window, self.symmetric)
        cooc.fit_sentences(self._token_lists())
        rows, cols, vals = cooc.as_arrays()

        rs = np.random.RandomState(self.seed)
        w = jnp.asarray((rs.rand(V, D).astype(np.float32) - 0.5) / D)
        wc = jnp.asarray((rs.rand(V, D).astype(np.float32) - 0.5) / D)
        b = jnp.zeros((V,), jnp.float32)
        bc = jnp.zeros((V,), jnp.float32)
        hw = jnp.ones((V, D), jnp.float32)
        hwc = jnp.ones((V, D), jnp.float32)
        hb = jnp.ones((V,), jnp.float32)
        hbc = jnp.ones((V,), jnp.float32)

        n = len(rows)
        B = self.batch_size
        for _ in range(self.epochs):
            perm = rs.permutation(n)
            for i0 in range(0, n, B):
                sel = perm[i0:i0 + B]
                pad = B - len(sel)
                mask = np.concatenate([np.ones(len(sel), np.float32),
                                       np.zeros(pad, np.float32)])
                r = np.concatenate([rows[sel], np.zeros(pad, np.int32)])
                c = np.concatenate([cols[sel], np.zeros(pad, np.int32)])
                x = np.concatenate([vals[sel], np.ones(pad, np.float32)])
                (w, wc, b, bc, hw, hwc, hb, hbc, loss) = self._glove_step(
                    w, wc, b, bc, hw, hwc, hb, hbc,
                    jnp.asarray(r), jnp.asarray(c), jnp.asarray(x),
                    jnp.asarray(mask), jnp.float32(self.learning_rate),
                    jnp.float32(self.x_max), jnp.float32(self.alpha))
                self.cum_loss += float(loss)

        # final vectors: w + w̃ (standard GloVe practice)
        self.lookup = InMemoryLookupTable(self.vocab, D, seed=self.seed,
                                          use_hs=False)
        self.lookup.syn0 = w + wc
        self.lookup._build_neg_cdf()
        return self

    class Builder:
        def __init__(self):
            self._kw = {}
            self._iterator = None
            self._tokenizer = None

        def iterate(self, iterator):
            if isinstance(iterator, (list, tuple)):
                iterator = CollectionSentenceIterator(iterator)
            self._iterator = iterator
            return self

        def tokenizer_factory(self, tf):
            self._tokenizer = tf
            return self

        def layer_size(self, n):
            self._kw["layer_size"] = n
            return self

        def window_size(self, n):
            self._kw["window"] = n
            return self

        def epochs(self, n):
            self._kw["epochs"] = n
            return self

        def learning_rate(self, lr):
            self._kw["learning_rate"] = lr
            return self

        def x_max(self, x):
            self._kw["x_max"] = x
            return self

        def alpha(self, a):
            self._kw["alpha"] = a
            return self

        def min_word_frequency(self, n):
            self._kw["min_word_frequency"] = n
            return self

        def batch_size(self, n):
            self._kw["batch_size"] = n
            return self

        def seed(self, s):
            self._kw["seed"] = s
            return self

        def symmetric(self, b):
            self._kw["symmetric"] = b
            return self

        def build(self) -> "Glove":
            if self._iterator is None:
                raise ValueError("Glove.Builder: iterate(...) required")
            return Glove(sentence_iterator=self._iterator,
                         tokenizer_factory=self._tokenizer, **self._kw)
