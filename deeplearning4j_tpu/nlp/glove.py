"""GloVe: co-occurrence counting + weighted-least-squares embedding fit.

Reference: ``models/glove/Glove.java``, ``models/glove/AbstractCoOccurrences
.java`` (streaming window-weighted co-occurrence counts spilled through
binary round/shadow buffers; 1/d weighting),
``models/embeddings/learning/impl/elements/GloVe.java`` (per-pair AdaGrad
update, xMax=100, alpha=0.75).

TPU redesign: counting accumulates in a host dict up to a pair budget, then
spills sorted (key=row*V+col, weight) runs to disk;
``SpillingCoOccurrences`` external-merges the runs (heap merge, duplicates
summed) and streams chunks — so the co-occurrence table is never required
to fit in RAM, the capability the reference's shadow-copy buffers provide.
The optimisation loop ships shuffled (row, col, Xij) batches to the jitted
``glove_step`` kernel (``nlp/learning.py``) — AdaGrad scatter updates on
device.
"""

from __future__ import annotations

import heapq
import os
import tempfile
from collections import defaultdict
from typing import Dict, Iterable, Iterator, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp import learning
from deeplearning4j_tpu.nlp.documents import CollectionSentenceIterator, SentenceIterator
from deeplearning4j_tpu.nlp.lookup import InMemoryLookupTable
from deeplearning4j_tpu.nlp.tokenization import DefaultTokenizerFactory, TokenizerFactory
from deeplearning4j_tpu.nlp.vocab import (
    Sequence,
    VocabCache,
    VocabConstructor,
    VocabWord,
)
from deeplearning4j_tpu.nlp.wordvectors import WordVectors


class CoOccurrences:
    """Symmetric window-weighted co-occurrence counts (weight 1/distance).
    ≙ ``AbstractCoOccurrences.java``."""

    def __init__(self, vocab: VocabCache, window: int = 15,
                 symmetric: bool = True):
        self.vocab = vocab
        self.window = window
        self.symmetric = symmetric
        self.counts: Dict[Tuple[int, int], float] = defaultdict(float)

    def _count_sentence(self, tokens: list) -> None:
        idx = [self.vocab.index_of(t) for t in tokens]
        idx = [i for i in idx if i >= 0]
        n = len(idx)
        for i in range(n):
            for d in range(1, self.window + 1):
                j = i + d
                if j >= n:
                    break
                w = 1.0 / d
                self.counts[(idx[i], idx[j])] += w
                if self.symmetric:
                    self.counts[(idx[j], idx[i])] += w

    def _after_sentence(self) -> None:
        """Hook: SpillingCoOccurrences flushes here when over budget."""

    def fit_sentences(self, token_lists: Iterable[list]) -> "CoOccurrences":
        for tokens in token_lists:
            self._count_sentence(tokens)
            self._after_sentence()
        return self

    def as_arrays(self):
        if not self.counts:
            return (np.empty(0, np.int32), np.empty(0, np.int32),
                    np.empty(0, np.float32))
        items = list(self.counts.items())
        rows = np.array([k[0] for k, _ in items], np.int32)
        cols = np.array([k[1] for k, _ in items], np.int32)
        vals = np.array([v for _, v in items], np.float32)
        return rows, cols, vals


class SpillingCoOccurrences(CoOccurrences):
    """Out-of-core co-occurrence counting (≙ ``AbstractCoOccurrences.java``'s
    binary spill files with shadow-copy round buffers, re-derived as sorted
    spill runs + external heap merge).

    Counts accumulate in the in-RAM dict until ``memory_pairs`` distinct
    pairs, then the dict is flushed as a sorted (uint64 key = row*V+col,
    float32 weight) run file.  ``stream_chunks`` heap-merges all runs plus
    the live dict, summing duplicate keys, and yields (rows, cols, vals)
    chunks — the full table never needs to fit in memory.
    """

    def __init__(self, vocab: VocabCache, window: int = 15,
                 symmetric: bool = True, memory_pairs: int = 2_000_000,
                 tmp_dir: Optional[str] = None):
        super().__init__(vocab, window, symmetric)
        self.memory_pairs = max(1, memory_pairs)
        self._owns_tmp = tmp_dir is None
        self._tmp_dir = tmp_dir or tempfile.mkdtemp(prefix="glove_cooc_")
        self._spills = []          # file paths of sorted runs
        self.n_spills = 0

    def _flush(self):
        if not self.counts:
            return
        V = len(self.vocab)
        keys = np.fromiter(
            (r * V + c for (r, c) in self.counts), np.uint64,
            count=len(self.counts))
        vals = np.fromiter(self.counts.values(), np.float32,
                           count=len(self.counts))
        order = np.argsort(keys, kind="stable")
        base = os.path.join(self._tmp_dir, f"run{self.n_spills:05d}")
        # raw .npy so merge can mmap and read block-wise (npz would force a
        # whole-run load, defeating the out-of-core point)
        np.save(base + ".keys.npy", keys[order])
        np.save(base + ".vals.npy", vals[order])
        self._spills.append(base)
        self.n_spills += 1
        self.counts.clear()

    def _after_sentence(self) -> None:
        if len(self.counts) >= self.memory_pairs:
            self._flush()

    @staticmethod
    def _iter_run(base: str, block: int = 1 << 16):
        """Stream one sorted run from disk in bounded blocks (mmap-backed;
        RAM is O(block), never O(run))."""
        keys = np.load(base + ".keys.npy", mmap_mode="r")
        vals = np.load(base + ".vals.npy", mmap_mode="r")
        for i in range(0, len(keys), block):
            yield from zip(keys[i:i + block].tolist(),
                           vals[i:i + block].tolist())

    def _run_streams(self) -> list:
        streams = [self._iter_run(base) for base in self._spills]
        if self.counts:
            V = len(self.vocab)
            items = sorted((r * V + c, v) for (r, c), v in self.counts.items())
            streams.append(iter(items))
        return streams

    def stream_chunks(self, chunk_size: int = 1 << 20
                      ) -> Iterator[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
        """Merged unique (rows, cols, vals) in key order, in bounded chunks."""
        V = len(self.vocab)
        merged = heapq.merge(*self._run_streams())
        keys, vals = [], []
        cur_key, cur_val = None, 0.0
        for k, v in merged:
            if k == cur_key:
                cur_val += v
                continue
            if cur_key is not None:
                keys.append(cur_key)
                vals.append(cur_val)
                if len(keys) >= chunk_size:
                    ka = np.asarray(keys, np.uint64)
                    yield ((ka // V).astype(np.int32),
                           (ka % V).astype(np.int32),
                           np.asarray(vals, np.float32))
                    keys, vals = [], []
            cur_key, cur_val = k, v
        if cur_key is not None:
            keys.append(cur_key)
            vals.append(cur_val)
        if keys:
            ka = np.asarray(keys, np.uint64)
            yield ((ka // V).astype(np.int32), (ka % V).astype(np.int32),
                   np.asarray(vals, np.float32))

    def as_arrays(self):
        """Materialise the merged table (compat path; spills permitting)."""
        parts = list(self.stream_chunks())
        if not parts:
            return (np.empty(0, np.int32), np.empty(0, np.int32),
                    np.empty(0, np.float32))
        return (np.concatenate([p[0] for p in parts]),
                np.concatenate([p[1] for p in parts]),
                np.concatenate([p[2] for p in parts]))

    def close(self):
        for base in self._spills:
            for suffix in (".keys.npy", ".vals.npy"):
                try:
                    os.unlink(base + suffix)
                except OSError:
                    pass
        self._spills = []
        if self._owns_tmp:
            try:
                os.rmdir(self._tmp_dir)
            except OSError:
                pass  # non-empty (foreign files) or already gone
            self._owns_tmp = False


class Glove(WordVectors):
    def __init__(self, config=None, sentence_iterator: SentenceIterator = None,
                 tokenizer_factory: TokenizerFactory = None,
                 layer_size: int = 100, window: int = 15, epochs: int = 5,
                 learning_rate: float = 0.05, x_max: float = 100.0,
                 alpha: float = 0.75, min_word_frequency: int = 1,
                 batch_size: int = 1024, seed: int = 12345,
                 symmetric: bool = True, memory_pairs: Optional[int] = None):
        self.sentence_iterator = sentence_iterator
        self.tokenizer_factory = tokenizer_factory or DefaultTokenizerFactory()
        self.layer_size = layer_size
        self.window = window
        self.epochs = epochs
        self.learning_rate = learning_rate
        self.x_max = x_max
        self.alpha = alpha
        self.min_word_frequency = min_word_frequency
        self.batch_size = batch_size
        self.seed = seed
        self.symmetric = symmetric
        self.memory_pairs = memory_pairs  # spill budget; None = in-RAM
        self.vocab: Optional[VocabCache] = None
        self.lookup: Optional[InMemoryLookupTable] = None
        self.cum_loss = 0.0

    def _token_lists(self):
        self.sentence_iterator.reset()
        while self.sentence_iterator.has_next():
            s = self.sentence_iterator.next_sentence()
            if s:
                toks = self.tokenizer_factory.create(s).tokens()
                if toks:
                    yield toks

    # seam for the distributed variant (DistributedGlove shards this)
    _glove_step = staticmethod(learning.glove_step)

    def _train_pairs(self, state, rows, cols, vals, rs):
        """One pass over a (rows, cols, vals) block in shuffled fixed-size
        batches through the jitted AdaGrad kernel."""
        (w, wc, b, bc, hw, hwc, hb, hbc) = state
        n = len(rows)
        B = self.batch_size
        perm = rs.permutation(n)
        for i0 in range(0, n, B):
            sel = perm[i0:i0 + B]
            pad = B - len(sel)
            mask = np.concatenate([np.ones(len(sel), np.float32),
                                   np.zeros(pad, np.float32)])
            r = np.concatenate([rows[sel], np.zeros(pad, np.int32)])
            c = np.concatenate([cols[sel], np.zeros(pad, np.int32)])
            x = np.concatenate([vals[sel], np.ones(pad, np.float32)])
            (w, wc, b, bc, hw, hwc, hb, hbc, loss) = self._glove_step(
                w, wc, b, bc, hw, hwc, hb, hbc,
                jnp.asarray(r), jnp.asarray(c), jnp.asarray(x),
                jnp.asarray(mask), jnp.float32(self.learning_rate),
                jnp.float32(self.x_max), jnp.float32(self.alpha))
            self.cum_loss += float(loss)
        return (w, wc, b, bc, hw, hwc, hb, hbc)

    def fit(self) -> "Glove":
        # vocab
        def seqs():
            for toks in self._token_lists():
                seq = Sequence()
                for t in toks:
                    seq.add_element(VocabWord(label=t))
                yield seq

        self.vocab = VocabConstructor(
            min_element_frequency=self.min_word_frequency).build_vocab(seqs())
        V, D = len(self.vocab), self.layer_size
        if self.memory_pairs:
            cooc = SpillingCoOccurrences(self.vocab, self.window,
                                         self.symmetric,
                                         memory_pairs=self.memory_pairs)
        else:
            cooc = CoOccurrences(self.vocab, self.window, self.symmetric)
        cooc.fit_sentences(self._token_lists())

        rs = np.random.RandomState(self.seed)
        w = jnp.asarray((rs.rand(V, D).astype(np.float32) - 0.5) / D)
        wc = jnp.asarray((rs.rand(V, D).astype(np.float32) - 0.5) / D)
        b = jnp.zeros((V,), jnp.float32)
        bc = jnp.zeros((V,), jnp.float32)
        hw = jnp.ones((V, D), jnp.float32)
        hwc = jnp.ones((V, D), jnp.float32)
        hb = jnp.ones((V,), jnp.float32)
        hbc = jnp.ones((V,), jnp.float32)
        state = (w, wc, b, bc, hw, hwc, hb, hbc)

        try:
            spilled = isinstance(cooc, SpillingCoOccurrences) and cooc.n_spills
            if spilled:
                # out-of-core: each epoch streams merged chunks; shuffling is
                # within-chunk (the reference's round-buffer pass has the same
                # locality), so RAM stays bounded by chunk_size
                for _ in range(self.epochs):
                    for rows, cols, vals in cooc.stream_chunks():
                        state = self._train_pairs(state, rows, cols, vals, rs)
            else:
                rows, cols, vals = cooc.as_arrays()
                for _ in range(self.epochs):
                    state = self._train_pairs(state, rows, cols, vals, rs)
        finally:  # spill files must not outlive a failed fit
            if isinstance(cooc, SpillingCoOccurrences):
                cooc.close()
        (w, wc, b, bc, hw, hwc, hb, hbc) = state

        # final vectors: w + w̃ (standard GloVe practice)
        self.lookup = InMemoryLookupTable(self.vocab, D, seed=self.seed,
                                          use_hs=False)
        self.lookup.syn0 = w + wc
        self.lookup._build_neg_cdf()
        return self

    class Builder:
        def __init__(self):
            self._kw = {}
            self._iterator = None
            self._tokenizer = None

        def iterate(self, iterator):
            if isinstance(iterator, (list, tuple)):
                iterator = CollectionSentenceIterator(iterator)
            self._iterator = iterator
            return self

        def tokenizer_factory(self, tf):
            self._tokenizer = tf
            return self

        def layer_size(self, n):
            self._kw["layer_size"] = n
            return self

        def window_size(self, n):
            self._kw["window"] = n
            return self

        def epochs(self, n):
            self._kw["epochs"] = n
            return self

        def learning_rate(self, lr):
            self._kw["learning_rate"] = lr
            return self

        def x_max(self, x):
            self._kw["x_max"] = x
            return self

        def alpha(self, a):
            self._kw["alpha"] = a
            return self

        def min_word_frequency(self, n):
            self._kw["min_word_frequency"] = n
            return self

        def batch_size(self, n):
            self._kw["batch_size"] = n
            return self

        def seed(self, s):
            self._kw["seed"] = s
            return self

        def max_memory_pairs(self, n):
            """Spill-to-disk budget: at most n distinct co-occurrence pairs
            held in RAM (reference maxMemory on AbstractCoOccurrences)."""
            self._kw["memory_pairs"] = n
            return self

        def symmetric(self, b):
            self._kw["symmetric"] = b
            return self

        def build(self) -> "Glove":
            if self._iterator is None:
                raise ValueError("Glove.Builder: iterate(...) required")
            return Glove(sentence_iterator=self._iterator,
                         tokenizer_factory=self._tokenizer, **self._kw)
