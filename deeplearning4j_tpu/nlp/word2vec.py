"""Word2Vec facade: tokenizer wiring over the SequenceVectors engine.

Reference: ``models/word2vec/Word2Vec.java`` (Builder: iterate/
tokenizerFactory/layerSize/windowSize/minWordFrequency/negativeSample/
learningRate/minLearningRate/epochs/iterations/seed/sampling/batchSize/
useHierarchicSoftmax) and ``models/word2vec/StaticWord2Vec.java``
(query-only table).
"""

from __future__ import annotations

from typing import Iterable, List, Optional

import numpy as np

from deeplearning4j_tpu.nlp.documents import CollectionSentenceIterator, SentenceIterator
from deeplearning4j_tpu.nlp.sequencevectors import SequenceVectors, VectorsConfiguration
from deeplearning4j_tpu.nlp.tokenization import DefaultTokenizerFactory, TokenizerFactory
from deeplearning4j_tpu.nlp.vocab import Sequence, VocabWord
from deeplearning4j_tpu.nlp.wordvectors import WordVectors


class Word2Vec(SequenceVectors):
    def __init__(self, config: VectorsConfiguration,
                 sentence_iterator: SentenceIterator,
                 tokenizer_factory: TokenizerFactory):
        self.sentence_iterator = sentence_iterator
        self.tokenizer_factory = tokenizer_factory
        super().__init__(config, self._sequences)

    def _sequences(self) -> Iterable[Sequence]:
        self.sentence_iterator.reset()
        while self.sentence_iterator.has_next():
            sentence = self.sentence_iterator.next_sentence()
            if not sentence:
                continue
            tokens = self.tokenizer_factory.create(sentence).tokens()
            if not tokens:
                continue
            seq = Sequence()
            for t in tokens:
                seq.add_element(VocabWord(label=t))
            yield seq

    class Builder:
        """≙ ``Word2Vec.Builder``."""

        def __init__(self):
            self._cfg = VectorsConfiguration()
            self._iterator: Optional[SentenceIterator] = None
            self._tokenizer: TokenizerFactory = DefaultTokenizerFactory()

        def iterate(self, iterator) -> "Word2Vec.Builder":
            if isinstance(iterator, (list, tuple)):
                iterator = CollectionSentenceIterator(iterator)
            self._iterator = iterator
            return self

        def tokenizer_factory(self, tf: TokenizerFactory) -> "Word2Vec.Builder":
            self._tokenizer = tf
            return self

        def layer_size(self, n: int):
            self._cfg.layer_size = n
            return self

        def window_size(self, n: int):
            self._cfg.window = n
            return self

        def min_word_frequency(self, n: int):
            self._cfg.min_word_frequency = n
            return self

        def negative_sample(self, n: int):
            self._cfg.negative = int(n)
            return self

        def use_hierarchic_softmax(self, b: bool):
            self._cfg.use_hierarchic_softmax = b
            return self

        def learning_rate(self, lr: float):
            self._cfg.learning_rate = lr
            return self

        def min_learning_rate(self, lr: float):
            self._cfg.min_learning_rate = lr
            return self

        def epochs(self, n: int):
            self._cfg.epochs = n
            return self

        def iterations(self, n: int):
            self._cfg.iterations = n
            return self

        def seed(self, s: int):
            self._cfg.seed = s
            return self

        def sampling(self, s: float):
            self._cfg.subsampling = s
            return self

        def batch_size(self, n: int):
            self._cfg.batch_size = n
            return self

        def elements_learning_algorithm(self, name: str):
            self._cfg.elements_algorithm = name.lower()
            return self

        def build(self) -> "Word2Vec":
            if self._iterator is None:
                raise ValueError("Word2Vec.Builder: iterate(...) is required")
            return Word2Vec(self._cfg, self._iterator, self._tokenizer)


class StaticWord2Vec(WordVectors):
    """Query-only vectors (no training). ≙ ``StaticWord2Vec.java``."""

    def __init__(self, vocab, lookup):
        self.vocab = vocab
        self.lookup = lookup
