"""Declarative UI component model: charts/tables/text as JSON.

Reference: ``deeplearning4j-ui-components/.../components/**`` —
ChartLine/ChartScatter/ChartHistogram/ChartStackedArea/ChartTimeline,
ComponentTable, ComponentText, ComponentDiv + Style classes, rendered by a
JS frontend from their JSON form.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple


class Component:
    component_type = "Component"

    def to_dict(self) -> Dict[str, Any]:
        raise NotImplementedError

    def to_json(self) -> str:
        return json.dumps(self.to_dict())


@dataclass
class StyleChart:
    """≙ ``components/chart/style/StyleChart.java`` (subset)."""

    width: float = 640
    height: float = 420
    title_color: str = "#333333"
    series_colors: Optional[List[str]] = None

    def to_dict(self):
        return {k: v for k, v in self.__dict__.items() if v is not None}


class ChartLine(Component):
    """≙ ``components/chart/ChartLine.java``."""

    component_type = "ChartLine"

    def __init__(self, title: str, style: Optional[StyleChart] = None):
        self.title = title
        self.style = style or StyleChart()
        self.series: List[Dict[str, Any]] = []

    def add_series(self, name: str, x: Sequence[float], y: Sequence[float]):
        self.series.append({"name": name, "x": list(map(float, x)),
                            "y": list(map(float, y))})
        return self

    def to_dict(self):
        return {"componentType": self.component_type, "title": self.title,
                "style": self.style.to_dict(), "series": self.series}


class ChartScatter(ChartLine):
    """≙ ``components/chart/ChartScatter.java``."""

    component_type = "ChartScatter"


class ChartHistogram(Component):
    """≙ ``components/chart/ChartHistogram.java``."""

    component_type = "ChartHistogram"

    def __init__(self, title: str, style: Optional[StyleChart] = None):
        self.title = title
        self.style = style or StyleChart()
        self.bins: List[Dict[str, float]] = []

    def add_bin(self, lower: float, upper: float, y: float):
        self.bins.append({"lower": float(lower), "upper": float(upper),
                          "y": float(y)})
        return self

    def to_dict(self):
        return {"componentType": self.component_type, "title": self.title,
                "style": self.style.to_dict(), "bins": self.bins}


class ChartStackedArea(ChartLine):
    """≙ ``components/chart/ChartStackedArea.java``."""

    component_type = "ChartStackedArea"


class ComponentTable(Component):
    """≙ ``components/table/ComponentTable.java``."""

    component_type = "ComponentTable"

    def __init__(self, header: Sequence[str],
                 rows: Sequence[Sequence[Any]] = ()):
        self.header = list(header)
        self.rows = [list(map(str, r)) for r in rows]

    def add_row(self, *cells):
        self.rows.append(list(map(str, cells)))
        return self

    def to_dict(self):
        return {"componentType": self.component_type, "header": self.header,
                "content": self.rows}


class ComponentText(Component):
    """≙ ``components/text/ComponentText.java``."""

    component_type = "ComponentText"

    def __init__(self, text: str):
        self.text = text

    def to_dict(self):
        return {"componentType": self.component_type, "text": self.text}


class ComponentDiv(Component):
    """≙ ``components/component/ComponentDiv.java`` — container."""

    component_type = "ComponentDiv"

    def __init__(self, *children: Component):
        self.children = list(children)

    def to_dict(self):
        return {"componentType": self.component_type,
                "components": [c.to_dict() for c in self.children]}


def component_from_dict(d: Dict[str, Any]) -> Component:
    t = d.get("componentType")
    if t in ("ChartLine", "ChartScatter", "ChartStackedArea"):
        cls = {"ChartLine": ChartLine, "ChartScatter": ChartScatter,
               "ChartStackedArea": ChartStackedArea}[t]
        c = cls(d["title"])
        for s in d.get("series", []):
            c.add_series(s["name"], s["x"], s["y"])
        return c
    if t == "ChartHistogram":
        c = ChartHistogram(d["title"])
        for b in d.get("bins", []):
            c.add_bin(b["lower"], b["upper"], b["y"])
        return c
    if t == "ComponentTable":
        return ComponentTable(d["header"], d.get("content", []))
    if t == "ComponentText":
        return ComponentText(d["text"])
    if t == "ComponentDiv":
        return ComponentDiv(*[component_from_dict(x)
                              for x in d.get("components", [])])
    raise ValueError(f"Unknown componentType '{t}'")
