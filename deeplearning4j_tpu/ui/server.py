"""Training UI server + remote stats listener.

Reference: ``deeplearning4j-ui/.../UiServer.java:25-33`` (Dropwizard/Jetty
app: REST endpoints + static assets + live charts) and
``deeplearning4j-ui-remote-iterationlisteners/.../RemoteFlowIterationListener
.java`` (train cluster POSTs stats to a remote UI host).

Redesign: stdlib ``http.server`` on a background thread; endpoints return
JSON from a StatsStorage; a single self-contained HTML page renders score
curves + histograms with inline SVG (no external JS, no CDN).  The remote
listener POSTs StatsReport JSON to ``/collect``.
"""

from __future__ import annotations

import json
import math
import queue as queue_mod
import threading
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional

from deeplearning4j_tpu.observability.health import (
    HealthEvaluator, default_training_rules,
)
from deeplearning4j_tpu.observability.metrics import get_registry
from deeplearning4j_tpu.optimize.listeners import IterationListener
from deeplearning4j_tpu.ui.stats import StatsReport, StatsUpdateConfiguration
from deeplearning4j_tpu.ui.storage import InMemoryStatsStorage, StatsStorage

# metric selectors the comparison / drill-down endpoints understand:
# plain report fields, or "<kind>:<layer>" per-layer introspection series
_REPORT_METRICS = {"score", "iteration_time_ms", "samples_per_second"}
_LAYER_METRICS = {
    "gradient_norm": ("gradient_stats", "norm"),
    "update_norm": ("update_stats", "norm"),
    "update_ratio": ("update_stats", "ratio"),
    "param_norm": ("update_stats", "param_norm"),
    "dead_fraction": ("activation_stats", "zero_fraction"),
    "activation_mean": ("activation_stats", "mean"),
    "activation_std": ("activation_stats", "std"),
}


def _metric_value(u: StatsReport, metric: str):
    """One report's value for a metric selector, or None."""
    if metric in _REPORT_METRICS:
        v = getattr(u, metric)
        return v if v is not None and not (isinstance(v, float)
                                           and math.isnan(v)) else None
    kind, _, layer = metric.partition(":")
    spec = _LAYER_METRICS.get(kind)
    if spec is None or not layer:
        raise ValueError(f"unknown metric '{metric}'")
    entry = (getattr(u, spec[0]) or {}).get(layer)
    if not entry:
        return None
    v = entry.get(spec[1])
    return v if v is not None and not (isinstance(v, float)
                                       and math.isnan(v)) else None

_PAGE = """<!DOCTYPE html>
<html><head><title>deeplearning4j_tpu training UI</title>
<style>
 body{font-family:sans-serif;margin:24px;background:#fafafa;color:#222}
 h1{font-size:20px} h2{font-size:16px;margin-top:28px}
 .card{background:#fff;border:1px solid #ddd;border-radius:6px;
       padding:12px;margin:12px 0;max-width:720px}
 svg{background:#fff} table{border-collapse:collapse}
 td,th{border:1px solid #ccc;padding:4px 8px;font-size:13px}
</style></head>
<body><h1>deeplearning4j_tpu training UI</h1><div id="root">loading…</div>
<script>
function poly(xs, ys, w, h, pad){
  const xmin=Math.min(...xs), xmax=Math.max(...xs);
  const ymin=Math.min(...ys), ymax=Math.max(...ys);
  const sx=x=>pad+(x-xmin)/Math.max(xmax-xmin,1e-9)*(w-2*pad);
  const sy=y=>h-pad-(y-ymin)/Math.max(ymax-ymin,1e-9)*(h-2*pad);
  return xs.map((x,i)=>`${sx(x).toFixed(1)},${sy(ys[i]).toFixed(1)}`).join(' ');
}
function lineChart(title, xs, ys){
  const w=680,h=260,p=30;
  return `<div class="card"><h2>${title}</h2>
   <svg width="${w}" height="${h}">
    <polyline fill="none" stroke="#1f77b4" stroke-width="1.5"
      points="${poly(xs,ys,w,h,p)}"/>
    <text x="${p}" y="14" font-size="11">last: ${ys[ys.length-1].toPrecision(5)}</text>
   </svg></div>`;
}
function histChart(title, bins, counts){
  const w=680,h=160,p=25; const maxc=Math.max(...counts,1);
  const bw=(w-2*p)/counts.length;
  const bars=counts.map((c,i)=>`<rect x="${(p+i*bw).toFixed(1)}"
    y="${(h-p-(c/maxc)*(h-2*p)).toFixed(1)}" width="${(bw-1).toFixed(1)}"
    height="${((c/maxc)*(h-2*p)).toFixed(1)}" fill="#2ca02c"/>`).join('');
  return `<div class="card"><h2>${title}</h2>
    <svg width="${w}" height="${h}">${bars}</svg></div>`;
}
async function refresh(){
  const sessions = await (await fetch('train/sessions')).json();
  let html='';
  for(const sid of sessions){
    const data = await (await fetch('train/overview?sid='+sid)).json();
    html += `<h2>session ${sid}</h2>`;
    if(data.iterations.length>1)
      html += lineChart('score vs iteration', data.iterations, data.scores);
    if(data.iteration_times.length>1)
      html += lineChart('iteration time (ms)', data.iterations, data.iteration_times);
    const intro = await (await fetch('train/introspection?sid='+sid)).json();
    for(const layer of (intro.layers||[]).slice(0,6)){
      const s = intro.series[layer]||{};
      const g=s.gradient_norm, r=s.update_ratio, d=s.dead_fraction;
      if(g && g.values.length>1)
        html += lineChart('gradient norm: '+layer, g.iterations, g.values);
      if(r && r.values.length>1)
        html += lineChart('update:param ratio: '+layer, r.iterations, r.values);
      if(d && d.values.some(v=>v>0))
        html += lineChart('dead fraction: '+layer, d.iterations, d.values);
    }
    const latest = data.latest_histograms || {};
    for(const k of Object.keys(latest).slice(0,8)){
      html += histChart('param histogram: '+k, latest[k].bins, latest[k].counts);
    }
  }
  document.getElementById('root').innerHTML = html || 'no sessions yet';
}
refresh(); setInterval(refresh, 3000);
// live view: any SSE update triggers an immediate redraw (polling stays
// as the fallback when EventSource is unavailable)
try{
  let pending = false;
  const es = new EventSource('train/stream');
  es.onmessage = () => {
    if(pending) return;
    pending = true;
    setTimeout(() => { pending = false; refresh(); }, 250);
  };
}catch(e){}
</script></body></html>
"""


class UIServer:
    """≙ ``UiServer.java``: hosts the dashboard + REST + /collect ingest.

    Operational endpoints (a training process embedding this server is
    scrape- and probe-able without a separate exporter):

    - ``GET /metrics`` — Prometheus text scrape of the process-wide
      metrics registry (fit/phase/compile/worker families).
    - ``GET /health`` — SLO verdict from a ``HealthEvaluator``
      (``health=`` to customize; defaults to ``default_training_rules()``:
      a recompile budget, plus whatever step-p99/throughput/straggler
      limits the caller configures); 200 healthy / 503 with the failing
      rules detailed.
    - ``GET /memory`` — the sharding ledger (per-tree per-device bytes,
      replication factors, ZeRO projection) plus per-program memory /
      collective accounting when a ``ShardStatsCollector`` is installed,
      and the PJRT device stats (docs/observability.md "Memory &
      communication").
    - ``GET /generation/cache`` — paged-pool occupancy + persistent
      prefix-cache stats of an attached ``GenerationEngine``
      (``attach_generation``); 404 until one is attached.
    - ``GET /fleet`` / ``GET /fleet/metrics`` — an attached
      ``FleetAggregator``'s per-worker table and its merged
      worker-labeled registry (``attach_fleet``); 404 until one is
      attached.
    """

    def __init__(self, storage: Optional[StatsStorage] = None, port: int = 0,
                 registry=None, health: Optional[HealthEvaluator] = None):
        self.storage = storage or InMemoryStatsStorage()
        self._registry = registry
        self.generation = None   # attach_generation()
        self.fleet = None        # attach_fleet()
        self.health = health or HealthEvaluator(
            default_training_rules(), component="training",
            registry=registry)
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._requested_port = port
        # set on stop(): live SSE handler threads poll it between
        # heartbeats so shutdown never waits on an open stream
        self._stopping = threading.Event()

    def attach_generation(self, engine) -> None:
        """Expose a ``GenerationEngine``'s cache stats on
        ``GET /generation/cache`` (the serving-side twin of /memory)."""
        self.generation = engine

    def attach_fleet(self, aggregator) -> None:
        """Expose a ``FleetAggregator``'s per-worker table on
        ``GET /fleet`` and its merged worker-labeled registry on
        ``GET /fleet/metrics`` — the training UI doubles as the
        fleet-operator console without running a second HTTP server."""
        self.fleet = aggregator

    # ------------------------------------------------------------- queries
    def compare_sessions(self, sids: List[str],
                         metric: str = "score") -> Dict[str, Any]:
        """Overlay N sessions' series by iteration — the run-comparison
        view (same LR sweep, before/after a fix, replica A vs B).
        ``metric``: a report field (``score``, ``iteration_time_ms``,
        ``samples_per_second``) or ``<kind>:<layer>`` with kind one of
        gradient_norm / update_norm / update_ratio / param_norm /
        dead_fraction / activation_mean / activation_std."""
        if metric not in _REPORT_METRICS:
            kind, _, layer = metric.partition(":")
            if kind not in _LAYER_METRICS or not layer:
                raise ValueError(f"unknown metric '{metric}'")
        sessions: Dict[str, Any] = {}
        for sid in sids:
            its, vals = [], []
            for u in self.storage.get_updates(sid):
                v = _metric_value(u, metric)
                if v is None:
                    continue
                its.append(u.iteration)
                vals.append(v)
            sessions[sid] = {"iterations": its, "values": vals}
        return {"metric": metric, "sessions": sessions}

    def layer_detail(self, sid: str, layer: str) -> Dict[str, Any]:
        """Per-layer drill-down as a UI component tree
        (``ui.components``): gradient/update-norm, update:param ratio,
        activation mean/std, dead fraction — per-replica series when the
        session ran under a data-parallel master — plus the layer's
        latest param histograms."""
        from deeplearning4j_tpu.ui.components import (
            ChartHistogram, ChartLine, ComponentDiv, ComponentTable,
        )

        ups = self.storage.get_updates(sid)
        div = ComponentDiv()

        def series_chart(title, metric):
            chart = ChartLine(title)
            its, vals = [], []
            for u in ups:
                v = _metric_value(u, f"{metric}:{layer}")
                if v is not None:
                    its.append(u.iteration)
                    vals.append(v)
            if its:
                chart.add_series(metric, its, vals)
            return chart, bool(its)

        for title, metric in (("gradient norm", "gradient_norm"),
                              ("update norm", "update_norm"),
                              ("update:param ratio", "update_ratio"),
                              ("activation mean", "activation_mean"),
                              ("activation std", "activation_std"),
                              ("dead fraction", "dead_fraction")):
            chart, has = series_chart(f"{layer}: {title}", metric)
            if has:
                div.children.append(chart)
        # per-replica gradient-norm overlay (wrapper runs)
        per_rep = ChartLine(f"{layer}: per-replica gradient norm")
        n_rep = 0
        for u in ups:
            entry = (u.gradient_stats or {}).get(layer) or {}
            n_rep = max(n_rep, len(entry.get("per_replica") or ()))
        for k in range(n_rep):
            its, vals = [], []
            for u in ups:
                col = ((u.gradient_stats or {}).get(layer) or {}).get(
                    "per_replica")
                if col is not None and k < len(col) \
                        and math.isfinite(col[k]):
                    its.append(u.iteration)
                    vals.append(col[k])
            if its:
                per_rep.add_series(f"replica {k}", its, vals)
        if per_rep.series:
            div.children.append(per_rep)
        if ups:
            last = ups[-1]
            for name, h in (last.param_histograms or {}).items():
                if not name.startswith(f"{layer}/"):
                    continue
                hist = ChartHistogram(f"param histogram: {name}")
                for lo, hi, c in zip(h["bins"][:-1], h["bins"][1:],
                                     h["counts"]):
                    hist.add_bin(lo, hi, c)
                div.children.append(hist)
            rows = []
            for metric in _LAYER_METRICS:
                v = _metric_value(last, f"{metric}:{layer}")
                if v is not None:
                    rows.append((metric, f"{v:.6g}"))
            if rows:
                div.children.append(
                    ComponentTable(["stat", "latest"], rows))
        return div.to_dict()

    def introspection_series(self, sid: str) -> Dict[str, Any]:
        """All per-layer introspection series of one session (feeds the
        dashboard's layer charts)."""
        ups = self.storage.get_updates(sid)
        layers: List[str] = []
        for u in ups:
            for name in (u.gradient_stats or {}):
                if name not in layers:
                    layers.append(name)
            for name in (u.activation_stats or {}):
                if name not in layers:
                    layers.append(name)
        out: Dict[str, Any] = {"layers": layers, "series": {}}
        for layer in layers:
            entry: Dict[str, Any] = {}
            for m in _LAYER_METRICS:
                # per-metric iteration axis: a NaN/absent value (e.g. a
                # guarded no-op step's ratio) is SKIPPED, never emitted
                # as null — a shared axis would force null padding and
                # crash/skew the dashboard's chart renderer
                its: List[int] = []
                vals: List[float] = []
                for u in ups:
                    v = _metric_value(u, f"{m}:{layer}")
                    if v is None:
                        continue
                    its.append(u.iteration)
                    vals.append(v)
                if vals:
                    entry[m] = {"iterations": its, "values": vals}
            out["series"][layer] = entry
        return out

    def numerics_report(self, sid: str) -> Dict[str, Any]:
        """The most recent precision-ledger harvest of one session plus
        its rendered operator table (``GET /train/numerics``)."""
        from deeplearning4j_tpu.observability import numerics
        ups = self.storage.get_updates(sid)
        latest = None
        for u in reversed(ups):
            if getattr(u, "numerics", None):
                latest = u.numerics
                break
        if latest is None:
            return {"numerics": None, "ledger": None}
        return {"numerics": latest,
                "ledger": numerics.format_precision_ledger(latest)}

    # ------------------------------------------------------------ lifecycle
    def start(self) -> int:
        storage = self.storage
        ui = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _json(self, obj, code=200):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _sse(self, sid: Optional[str], replay: bool) -> None:
                """Server-Sent-Events live stream of StatsReport updates
                (``sid=`` filters to one session; ``replay=1`` first
                replays the stored history, so a late-attaching client —
                or a post-crash reopen of a FileStatsStorage — sees the
                whole run).  Heartbeats every second keep dead-client
                detection prompt; the stream ends on client disconnect
                or server stop."""
                q: "queue_mod.Queue" = queue_mod.Queue(maxsize=1024)

                def on_update(rep):
                    if sid and rep.session_id != sid:
                        return
                    try:
                        q.put_nowait(rep)
                    except queue_mod.Full:
                        pass   # slow client: drop rather than block training

                storage.add_listener(on_update)
                try:
                    self.send_response(200)
                    self.send_header("Content-Type", "text/event-stream")
                    self.send_header("Cache-Control", "no-cache")
                    self.end_headers()
                    if replay:
                        sids = [sid] if sid else storage.list_session_ids()
                        for s in sids:
                            for rep in storage.get_updates(s):
                                self._event(rep)
                    while not ui._stopping.is_set():
                        try:
                            rep = q.get(timeout=1.0)
                        except queue_mod.Empty:
                            self.wfile.write(b": keep-alive\n\n")
                            self.wfile.flush()
                            continue
                        self._event(rep)
                except (BrokenPipeError, ConnectionResetError, OSError):
                    pass   # client went away — normal stream teardown
                finally:
                    storage.remove_listener(on_update)

            def _event(self, rep) -> None:
                self.wfile.write(b"data: " + rep.to_json().encode()
                                 + b"\n\n")
                self.wfile.flush()

            def do_GET(self):
                path, _, query = self.path.partition("?")
                params = {k: urllib.parse.unquote(v) for k, v in
                          (p.split("=", 1) for p in query.split("&")
                           if "=" in p)}
                if path.endswith("/train/stream") or path == "/stream":
                    self._sse(params.get("sid"),
                              params.get("replay") in ("1", "true"))
                elif path.endswith("/train/compare") or path == "/compare":
                    sids = [s for s in params.get("sids", "").split(",") if s]
                    try:
                        self._json(ui.compare_sessions(
                            sids, params.get("metric", "score")))
                    except ValueError as e:
                        self._json({"error": str(e)}, 400)
                elif path.endswith("/train/layer"):
                    sid, layer = params.get("sid"), params.get("layer")
                    if not sid or not layer:
                        self._json({"error": "sid= and layer= required"},
                                   400)
                    else:
                        self._json(ui.layer_detail(sid, layer))
                elif path.endswith("/train/introspection"):
                    self._json(ui.introspection_series(params.get("sid")))
                elif path.endswith("/train/numerics"):
                    self._json(ui.numerics_report(params.get("sid")))
                elif path in ("/", "/train", "/train/"):
                    body = _PAGE.encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "text/html")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif path.endswith("/train/sessions") or path == "/sessions":
                    self._json(storage.list_session_ids())
                elif path.endswith("/train/overview") or path == "/overview":
                    sid = params.get("sid")
                    ups = storage.get_updates(sid) if sid else []
                    latest_hist = {}
                    if ups and ups[-1].param_histograms:
                        latest_hist = ups[-1].param_histograms
                    self._json({
                        "iterations": [u.iteration for u in ups],
                        "scores": [u.score for u in ups],
                        "iteration_times": [u.iteration_time_ms for u in ups],
                        "latest_histograms": latest_hist,
                    })
                elif path.endswith("/train/memory"):
                    sid = params.get("sid")
                    ups = storage.get_updates(sid) if sid else []
                    self._json([u.memory for u in ups])
                elif path == "/metrics":
                    reg = (ui._registry if ui._registry is not None
                           else get_registry())
                    body = reg.to_prometheus().encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type",
                        "text/plain; version=0.0.4; charset=utf-8")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif path == "/memory":
                    # the sharding ledger + per-program memory/collective
                    # accounting (docs/observability.md "Memory &
                    # communication"); device stats ride along so one
                    # probe answers "what holds the HBM and why"
                    from deeplearning4j_tpu.observability import shardstats
                    from deeplearning4j_tpu.observability.memory import (
                        device_memory_stats,
                    )

                    coll = shardstats.active_collector()
                    self._json({
                        "ledgers": shardstats.latest_ledgers(),
                        "programs": (coll.programs() if coll is not None
                                     else {}),
                        "device_memory": device_memory_stats(),
                    })
                elif path == "/generation/cache":
                    # a serving-side panel in the training UI: the
                    # attached generation engine's paged-pool occupancy
                    # + persistent prefix-cache stats
                    if ui.generation is None:
                        self._json({"error": "no generation engine "
                                    "attached (UIServer."
                                    "attach_generation)"}, 404)
                    else:
                        self._json(ui.generation.cache_stats())
                elif path == "/fleet":
                    # per-worker snapshot table + staleness (the
                    # aggregator's own /fleet, mirrored into the UI)
                    if ui.fleet is None:
                        self._json({"error": "no fleet aggregator "
                                    "attached (UIServer.attach_fleet)"},
                                   404)
                    else:
                        self._json(ui.fleet.fleet_table())
                elif path == "/fleet/metrics":
                    if ui.fleet is None:
                        self._json({"error": "no fleet aggregator "
                                    "attached (UIServer.attach_fleet)"},
                                   404)
                    else:
                        reg = ui.fleet.registry()
                        ui.fleet.evaluate_health(reg)
                        body = reg.to_prometheus().encode()
                        self.send_response(200)
                        self.send_header(
                            "Content-Type",
                            "text/plain; version=0.0.4; charset=utf-8")
                        self.send_header("Content-Length",
                                         str(len(body)))
                        self.end_headers()
                        self.wfile.write(body)
                elif path == "/health":
                    verdict = ui.health.evaluate()
                    self._json(verdict.to_dict(),
                               code=200 if verdict.healthy else 503)
                else:
                    self._json({"error": "not found", "path": path}, 404)

            def do_POST(self):
                if self.path.rstrip("/").endswith("/collect"):
                    n = int(self.headers.get("Content-Length", 0))
                    rep = StatsReport.from_json(self.rfile.read(n).decode())
                    storage.put_update(rep)
                    self._json({"ok": True})
                else:
                    self._json({"error": "not found"}, 404)

        self._stopping.clear()
        self._httpd = ThreadingHTTPServer(("127.0.0.1", self._requested_port),
                                          Handler)
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self._httpd.server_address[1]

    @property
    def port(self) -> Optional[int]:
        return self._httpd.server_address[1] if self._httpd else None

    def stop(self) -> None:
        self._stopping.set()   # unblock live SSE streams within one beat
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            # shutdown() unblocked serve_forever — bounded join so a
            # stop/start cycle never races the old acceptor thread
            self._thread.join(timeout=5.0)
            self._thread = None


class RemoteStatsListener(IterationListener):
    """POSTs per-iteration StatsReports to a remote UI server.
    ≙ ``RemoteFlowIterationListener.java`` (train host ≠ UI host)."""

    def __init__(self, url: str, session_id: str = "remote",
                 frequency: int = 1, timeout: float = 2.0):
        self.url = url.rstrip("/") + "/collect"
        self.session_id = session_id
        self.frequency = frequency
        self.timeout = timeout

    def iteration_done(self, model, iteration: int) -> None:
        if iteration % max(self.frequency, 1) != 0:
            return
        import time as _time

        rep = StatsReport(session_id=self.session_id, iteration=iteration,
                          timestamp=_time.time(),
                          score=float(getattr(model, "score_value", float("nan"))))
        data = rep.to_json().encode()
        req = urllib.request.Request(
            self.url, data=data, headers={"Content-Type": "application/json"})
        try:
            urllib.request.urlopen(req, timeout=self.timeout)
        except Exception:
            pass  # UI down must never kill training (reference behavior)
