"""Training UI server + remote stats listener.

Reference: ``deeplearning4j-ui/.../UiServer.java:25-33`` (Dropwizard/Jetty
app: REST endpoints + static assets + live charts) and
``deeplearning4j-ui-remote-iterationlisteners/.../RemoteFlowIterationListener
.java`` (train cluster POSTs stats to a remote UI host).

Redesign: stdlib ``http.server`` on a background thread; endpoints return
JSON from a StatsStorage; a single self-contained HTML page renders score
curves + histograms with inline SVG (no external JS, no CDN).  The remote
listener POSTs StatsReport JSON to ``/collect``.
"""

from __future__ import annotations

import json
import threading
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from deeplearning4j_tpu.observability.health import (
    HealthEvaluator, default_training_rules,
)
from deeplearning4j_tpu.observability.metrics import get_registry
from deeplearning4j_tpu.optimize.listeners import IterationListener
from deeplearning4j_tpu.ui.stats import StatsReport, StatsUpdateConfiguration
from deeplearning4j_tpu.ui.storage import InMemoryStatsStorage, StatsStorage

_PAGE = """<!DOCTYPE html>
<html><head><title>deeplearning4j_tpu training UI</title>
<style>
 body{font-family:sans-serif;margin:24px;background:#fafafa;color:#222}
 h1{font-size:20px} h2{font-size:16px;margin-top:28px}
 .card{background:#fff;border:1px solid #ddd;border-radius:6px;
       padding:12px;margin:12px 0;max-width:720px}
 svg{background:#fff} table{border-collapse:collapse}
 td,th{border:1px solid #ccc;padding:4px 8px;font-size:13px}
</style></head>
<body><h1>deeplearning4j_tpu training UI</h1><div id="root">loading…</div>
<script>
function poly(xs, ys, w, h, pad){
  const xmin=Math.min(...xs), xmax=Math.max(...xs);
  const ymin=Math.min(...ys), ymax=Math.max(...ys);
  const sx=x=>pad+(x-xmin)/Math.max(xmax-xmin,1e-9)*(w-2*pad);
  const sy=y=>h-pad-(y-ymin)/Math.max(ymax-ymin,1e-9)*(h-2*pad);
  return xs.map((x,i)=>`${sx(x).toFixed(1)},${sy(ys[i]).toFixed(1)}`).join(' ');
}
function lineChart(title, xs, ys){
  const w=680,h=260,p=30;
  return `<div class="card"><h2>${title}</h2>
   <svg width="${w}" height="${h}">
    <polyline fill="none" stroke="#1f77b4" stroke-width="1.5"
      points="${poly(xs,ys,w,h,p)}"/>
    <text x="${p}" y="14" font-size="11">last: ${ys[ys.length-1].toPrecision(5)}</text>
   </svg></div>`;
}
function histChart(title, bins, counts){
  const w=680,h=160,p=25; const maxc=Math.max(...counts,1);
  const bw=(w-2*p)/counts.length;
  const bars=counts.map((c,i)=>`<rect x="${(p+i*bw).toFixed(1)}"
    y="${(h-p-(c/maxc)*(h-2*p)).toFixed(1)}" width="${(bw-1).toFixed(1)}"
    height="${((c/maxc)*(h-2*p)).toFixed(1)}" fill="#2ca02c"/>`).join('');
  return `<div class="card"><h2>${title}</h2>
    <svg width="${w}" height="${h}">${bars}</svg></div>`;
}
async function refresh(){
  const sessions = await (await fetch('train/sessions')).json();
  let html='';
  for(const sid of sessions){
    const data = await (await fetch('train/overview?sid='+sid)).json();
    html += `<h2>session ${sid}</h2>`;
    if(data.iterations.length>1)
      html += lineChart('score vs iteration', data.iterations, data.scores);
    if(data.iteration_times.length>1)
      html += lineChart('iteration time (ms)', data.iterations, data.iteration_times);
    const latest = data.latest_histograms || {};
    for(const k of Object.keys(latest).slice(0,8)){
      html += histChart('param histogram: '+k, latest[k].bins, latest[k].counts);
    }
  }
  document.getElementById('root').innerHTML = html || 'no sessions yet';
}
refresh(); setInterval(refresh, 3000);
</script></body></html>
"""


class UIServer:
    """≙ ``UiServer.java``: hosts the dashboard + REST + /collect ingest.

    Operational endpoints (a training process embedding this server is
    scrape- and probe-able without a separate exporter):

    - ``GET /metrics`` — Prometheus text scrape of the process-wide
      metrics registry (fit/phase/compile/worker families).
    - ``GET /health`` — SLO verdict from a ``HealthEvaluator``
      (``health=`` to customize; defaults to ``default_training_rules()``:
      a recompile budget, plus whatever step-p99/throughput/straggler
      limits the caller configures); 200 healthy / 503 with the failing
      rules detailed.
    """

    def __init__(self, storage: Optional[StatsStorage] = None, port: int = 0,
                 registry=None, health: Optional[HealthEvaluator] = None):
        self.storage = storage or InMemoryStatsStorage()
        self._registry = registry
        self.health = health or HealthEvaluator(
            default_training_rules(), component="training",
            registry=registry)
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._requested_port = port

    # ------------------------------------------------------------ lifecycle
    def start(self) -> int:
        storage = self.storage
        ui = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _json(self, obj, code=200):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path, _, query = self.path.partition("?")
                params = dict(p.split("=", 1) for p in query.split("&") if "=" in p)
                if path in ("/", "/train", "/train/"):
                    body = _PAGE.encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "text/html")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif path.endswith("/train/sessions") or path == "/sessions":
                    self._json(storage.list_session_ids())
                elif path.endswith("/train/overview") or path == "/overview":
                    sid = params.get("sid")
                    ups = storage.get_updates(sid) if sid else []
                    latest_hist = {}
                    if ups and ups[-1].param_histograms:
                        latest_hist = ups[-1].param_histograms
                    self._json({
                        "iterations": [u.iteration for u in ups],
                        "scores": [u.score for u in ups],
                        "iteration_times": [u.iteration_time_ms for u in ups],
                        "latest_histograms": latest_hist,
                    })
                elif path.endswith("/train/memory"):
                    sid = params.get("sid")
                    ups = storage.get_updates(sid) if sid else []
                    self._json([u.memory for u in ups])
                elif path == "/metrics":
                    reg = (ui._registry if ui._registry is not None
                           else get_registry())
                    body = reg.to_prometheus().encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type",
                        "text/plain; version=0.0.4; charset=utf-8")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif path == "/health":
                    verdict = ui.health.evaluate()
                    self._json(verdict.to_dict(),
                               code=200 if verdict.healthy else 503)
                else:
                    self._json({"error": "not found", "path": path}, 404)

            def do_POST(self):
                if self.path.rstrip("/").endswith("/collect"):
                    n = int(self.headers.get("Content-Length", 0))
                    rep = StatsReport.from_json(self.rfile.read(n).decode())
                    storage.put_update(rep)
                    self._json({"ok": True})
                else:
                    self._json({"error": "not found"}, 404)

        self._httpd = ThreadingHTTPServer(("127.0.0.1", self._requested_port),
                                          Handler)
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self._httpd.server_address[1]

    @property
    def port(self) -> Optional[int]:
        return self._httpd.server_address[1] if self._httpd else None

    def stop(self) -> None:
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            # shutdown() unblocked serve_forever — bounded join so a
            # stop/start cycle never races the old acceptor thread
            self._thread.join(timeout=5.0)
            self._thread = None


class RemoteStatsListener(IterationListener):
    """POSTs per-iteration StatsReports to a remote UI server.
    ≙ ``RemoteFlowIterationListener.java`` (train host ≠ UI host)."""

    def __init__(self, url: str, session_id: str = "remote",
                 frequency: int = 1, timeout: float = 2.0):
        self.url = url.rstrip("/") + "/collect"
        self.session_id = session_id
        self.frequency = frequency
        self.timeout = timeout

    def iteration_done(self, model, iteration: int) -> None:
        if iteration % max(self.frequency, 1) != 0:
            return
        import time as _time

        rep = StatsReport(session_id=self.session_id, iteration=iteration,
                          timestamp=_time.time(),
                          score=float(getattr(model, "score_value", float("nan"))))
        data = rep.to_json().encode()
        req = urllib.request.Request(
            self.url, data=data, headers={"Content-Type": "application/json"})
        try:
            urllib.request.urlopen(req, timeout=self.timeout)
        except Exception:
            pass  # UI down must never kill training (reference behavior)
