"""Weight/activation rendering — grids + PNG export, dependency-free.

Reference: ``deeplearning4j-ui/.../weights/ConvolutionalIterationListener.java``
(renders per-channel conv activations as an image grid each N iterations)
and the render utils under ``deeplearning4j-core/.../plot``.  PNG encoding
is a minimal grayscale writer (zlib + struct), so no imaging dependency.
"""

from __future__ import annotations

import struct
import zlib
from pathlib import Path
from typing import Optional

import numpy as np

from deeplearning4j_tpu.optimize.listeners import IterationListener


def normalize01(arr: np.ndarray) -> np.ndarray:
    arr = np.asarray(arr, np.float32)
    lo, hi = float(arr.min()), float(arr.max())
    if hi - lo < 1e-12:
        return np.zeros_like(arr)
    return (arr - lo) / (hi - lo)


def activation_grid(activations: np.ndarray, pad: int = 1,
                    channels_last: bool = True) -> np.ndarray:
    """Channel maps -> one [rows*H, cols*W] grid, each channel normalized
    independently (reference grid rendering).  Layout is explicit
    (channels_last: [H, W, C]; else [C, H, W]) — shape-based guessing is
    ambiguous when C and H/W are close."""
    a = np.asarray(activations)
    if a.ndim != 3:
        raise ValueError(f"expected 3-D channel maps, got shape {a.shape}")
    if not channels_last:  # [C, H, W] -> [H, W, C]
        a = np.transpose(a, (1, 2, 0))
    h, w, c = a.shape
    cols = int(np.ceil(np.sqrt(c)))
    rows = int(np.ceil(c / cols))
    grid = np.zeros((rows * (h + pad) - pad, cols * (w + pad) - pad),
                    np.float32)
    for i in range(c):
        r, col = divmod(i, cols)
        grid[r * (h + pad):r * (h + pad) + h,
             col * (w + pad):col * (w + pad) + w] = normalize01(a[:, :, i])
    return grid


def write_png(path, image01: np.ndarray) -> None:
    """Write a [H, W] float array in [0,1] as an 8-bit grayscale PNG."""
    img = np.clip(np.asarray(image01, np.float32), 0, 1)
    if img.ndim != 2:
        raise ValueError(f"expected 2-D image, got shape {img.shape}")
    data = (img * 255).astype(np.uint8)
    h, w = data.shape
    raw = b"".join(b"\x00" + data[r].tobytes() for r in range(h))

    def chunk(tag: bytes, payload: bytes) -> bytes:
        return (struct.pack(">I", len(payload)) + tag + payload
                + struct.pack(">I", zlib.crc32(tag + payload)))

    png = (b"\x89PNG\r\n\x1a\n"
           + chunk(b"IHDR", struct.pack(">IIBBBBB", w, h, 8, 0, 0, 0, 0))
           + chunk(b"IDAT", zlib.compress(raw))
           + chunk(b"IEND", b""))
    Path(path).write_bytes(png)


class ConvolutionalIterationListener(IterationListener):
    """Every `frequency` iterations, renders the first conv-shaped
    activation of a probe input to a PNG grid in `out_dir`.
    ≙ ``ConvolutionalIterationListener.java``."""

    def __init__(self, probe_input: np.ndarray, out_dir,
                 frequency: int = 10, layer_index: Optional[int] = None):
        self.probe = np.asarray(probe_input)
        self.out_dir = Path(out_dir)
        self.out_dir.mkdir(parents=True, exist_ok=True)
        self.frequency = max(frequency, 1)
        self.layer_index = layer_index
        self.rendered = []

    def iteration_done(self, model, iteration: int) -> None:
        if iteration % self.frequency != 0:
            return
        acts = model.feed_forward(self.probe[:1])
        if isinstance(acts, dict):  # ComputationGraph: name -> activation
            # drop input vertices so index semantics match the MLN list
            # (acts is seeded with the raw inputs, which are also rank-4)
            inputs = set(getattr(model.conf, "inputs", ()))
            acts = [a for name, a in acts.items() if name not in inputs]
        chosen = None
        for i, a in enumerate(acts):
            arr = np.asarray(a)
            if self.layer_index is not None:
                if i == self.layer_index:
                    if arr.ndim == 4:
                        chosen = arr
                    break  # non-conv selection: skip silently, don't kill fit
            elif arr.ndim == 4:  # [b, h, w, c]
                chosen = arr
                break
        if chosen is None:
            return
        grid = activation_grid(chosen[0])
        path = self.out_dir / f"activations_iter{iteration}.png"
        write_png(path, grid)
        self.rendered.append(path)
