"""Training stats collection: the StatsListener pipeline.

Reference: ``deeplearning4j-ui-model/.../stats/StatsListener.java`` (score,
timing, JVM/GC memory :183-196, param/update/activation histograms & summary
stats :230-244 at configurable frequency), ``stats/api/
StatsUpdateConfiguration.java``, SBE-encoded ``Persistable`` records
(``stats/sbe/*``), ``stats/impl/SbeStatsReport.java``.

TPU redesign: histograms/summary stats are computed ON DEVICE in one jitted
pass per collection (a handful of reductions fused by XLA), shipped as a
single small dict; records are JSON-serialisable dataclasses (replacing the
SBE codegen — a compact self-describing encoding with no schema compiler).
Device memory comes from PJRT ``memory_stats()`` instead of JVM MX beans.
"""

from __future__ import annotations

import itertools
import json
import time
from dataclasses import asdict, dataclass, field, fields
from functools import partial
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.observability.memory import (
    device_memory_stats,  # re-exported: ui.device_memory_stats is public API
    sample_once as _sample_device_memory,
)
from deeplearning4j_tpu.optimize.listeners import IterationListener

# Monotonic per-process suffix for generated session ids: two listeners
# created in the same millisecond must NOT silently interleave their
# reports into one session (the old ms-timestamp ids collided).
_SESSION_SEQ = itertools.count()


def _new_session_id(prefix: str) -> str:
    return f"{prefix}_{int(time.time() * 1000)}_{next(_SESSION_SEQ)}"


@dataclass
class StatsUpdateConfiguration:
    """≙ ``stats/api/StatsUpdateConfiguration.java``."""

    reporting_frequency: int = 1
    collect_score: bool = True
    collect_timing: bool = True
    collect_memory: bool = True
    collect_histograms_params: bool = True
    collect_histograms_updates: bool = False
    collect_histograms_activations: bool = False
    collect_mean_magnitudes: bool = True
    num_histogram_bins: int = 20
    # training introspection (device-side per-layer gradient/update/
    # activation stats, docs/observability.md): harvested into the
    # report when the model's conf enables it; anomaly_detection runs
    # the AnomalyMonitor rules on each harvested report
    collect_introspection: bool = True
    anomaly_detection: bool = True
    # precision ledger (device-side per-layer dynamic-range / format-
    # safety stats, docs/observability.md "Numerics"): harvested into
    # the report when the model's conf enables it; anomaly_detection
    # also runs the NumericsMonitor format-safety rules on each harvest
    collect_numerics: bool = True


@dataclass
class StatsInitializationReport:
    """Session-start record. ≙ ``SbeStatsInitializationReport``."""

    session_id: str
    model_class: str
    num_params: int
    num_layers: int
    start_time: float
    backend: str
    device_count: int
    model_config_json: Optional[str] = None

    def to_json(self) -> str:
        return json.dumps({"type": "init", **asdict(self)})


@dataclass
class StatsReport:
    """Per-collection record. ≙ ``SbeStatsReport``."""

    session_id: str
    iteration: int
    timestamp: float
    score: float = float("nan")
    iteration_time_ms: float = 0.0
    samples_per_second: float = 0.0
    memory: Dict[str, Any] = field(default_factory=dict)
    param_histograms: Dict[str, Any] = field(default_factory=dict)
    update_histograms: Dict[str, Any] = field(default_factory=dict)
    param_stats: Dict[str, Any] = field(default_factory=dict)
    learning_rate: float = float("nan")
    # training introspection (device-computed, one transfer per report):
    # per-layer {"norm", ["per_replica"]}, {"norm", "ratio",
    # "param_norm"}, {"mean", "std", "zero_fraction"}; replicas is the
    # data-parallel replica count when the stats are per-replica
    gradient_stats: Dict[str, Any] = field(default_factory=dict)
    update_stats: Dict[str, Any] = field(default_factory=dict)
    activation_stats: Dict[str, Any] = field(default_factory=dict)
    replicas: Optional[int] = None
    # precision ledger (device-computed, one transfer per report):
    # {"iteration", "loss_scale", "gradients"/"moments"/"activations":
    # {layer: {"max_abs", "underflow", "overflow",
    # "exponent_histogram", "verdicts"}}}
    numerics: Dict[str, Any] = field(default_factory=dict)

    def to_json(self) -> str:
        return json.dumps({"type": "update", **asdict(self)})

    @staticmethod
    def from_json(s: str) -> "StatsReport":
        d = json.loads(s)
        d.pop("type", None)
        return StatsReport.from_dict(d)

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "StatsReport":
        # forward-compatible: fields a NEWER writer added are dropped
        # instead of raising, so mixed-version FileStatsStorage files
        # stay readable
        known = {f.name for f in fields(StatsReport)}
        return StatsReport(**{k: v for k, v in d.items() if k in known})


@partial(jax.jit, static_argnums=(1,))
def _summary_and_histogram(flat, bins):
    """One fused device pass: min/max/mean/stdev/mean-magnitude + histogram."""
    mn, mx = flat.min(), flat.max()
    mean = flat.mean()
    std = flat.std()
    mean_mag = jnp.abs(flat).mean()
    span = jnp.maximum(mx - mn, 1e-12)
    edges = mn + span * jnp.arange(bins + 1) / bins
    idx = jnp.clip(((flat - mn) / span * bins).astype(jnp.int32), 0, bins - 1)
    counts = jnp.zeros((bins,), jnp.int32).at[idx].add(1)
    return mn, mx, mean, std, mean_mag, edges, counts


@partial(jax.jit, static_argnums=(1,))
def _summary_stack(flats, bins):
    """ALL leaves' summaries in one device program: [N, 5] summary rows
    (min/max/mean/std/mean-magnitude), [N, bins+1] edges, [N, bins]
    counts — stacked so the caller pulls everything with ONE host
    transfer per report (the old per-tensor path paid five scalar
    ``float()`` syncs plus two ``np.asarray`` pulls per tensor)."""
    rows, edges, counts = [], [], []
    for flat in flats:
        flat = flat.astype(jnp.float32)
        mn, mx, mean, std, mm, e, c = _summary_and_histogram.__wrapped__(
            flat, bins)
        rows.append(jnp.stack([mn, mx, mean, std, mm]))
        edges.append(e)
        counts.append(c)
    return jnp.stack(rows), jnp.stack(edges), jnp.stack(counts)


def _leaf_entries(tree):
    """(name, flat array) per param leaf, walking nested subtrees
    (composite layers) in sorted key order."""
    out = []

    def walk(prefix, t):
        if isinstance(t, dict):
            for k in sorted(t):
                walk(prefix + (str(k),), t[k])
        elif t is not None:
            out.append(("/".join(prefix), jnp.ravel(jnp.asarray(t))))

    for layer, params in tree.items():
        if params:
            walk((str(layer),), params)
    return out


def _tensor_stats(tree, bins: int) -> Dict[str, Any]:
    entries = _leaf_entries(tree)
    if not entries:
        return {}
    names = [n for n, _ in entries]
    rows, edges, counts = _summary_stack(tuple(f for _, f in entries), bins)
    # the report's single batched device->host transfer
    rows, edges, counts = jax.device_get((rows, edges, counts))
    out = {}
    for i, name in enumerate(names):
        mn, mx, mean, std, mm = (float(v) for v in rows[i])
        out[name] = {
            "min": mn, "max": mx, "mean": mean, "stdev": std,
            "mean_magnitude": mm,
            "bins": [float(v) for v in edges[i]],
            "counts": [int(v) for v in counts[i]],
        }
    return out


# device_memory_stats moved to observability.memory (PJRT per-device memory,
# ≙ JVM memory MX beans in the reference); imported above for back-compat.


class StatsListener(IterationListener):
    """Collects per-iteration stats into a StatsStorage router.
    ≙ ``StatsListener.java``.

    Timing/throughput come from the shared metrics registry (the fit loops
    record ``dl4j_fit_last_step_seconds`` / ``dl4j_fit_samples_per_second``
    around the actual step dispatch) instead of re-deriving them from this
    listener's own wall clock; the clock remains as a fallback for custom
    training loops that bypass the instrumented facades."""

    def __init__(self, storage, session_id: Optional[str] = None,
                 config: Optional[StatsUpdateConfiguration] = None,
                 registry=None, anomaly_monitor=None):
        self.storage = storage
        self.session_id = session_id or _new_session_id("session")
        self.config = config or StatsUpdateConfiguration()
        self.registry = registry
        self._anomaly = anomaly_monitor   # lazily defaulted on first use
        self._num_anomaly = None          # NumericsMonitor, same lifecycle
        self._last_time: Optional[float] = None
        self._initialized = False

    def _registry_timing(self, model):
        """(step_seconds, samples_per_sec) for THIS model, or Nones.

        The fit loops stamp ``last_step_seconds`` / ``last_samples_per_
        second`` on the model instance (identity-correct even with several
        same-class models in one process); the kind-labeled registry gauges
        are NOT used here precisely because they would cross-contaminate."""
        return (getattr(model, "last_step_seconds", None),
                getattr(model, "last_samples_per_second", None))

    def _init_report(self, model) -> None:
        rep = StatsInitializationReport(
            session_id=self.session_id,
            model_class=type(model).__name__,
            num_params=model.num_params() if hasattr(model, "num_params") else 0,
            num_layers=len(getattr(model, "layers", [])) or
                       len(getattr(getattr(model, "conf", None), "nodes", [])),
            start_time=time.time(),
            backend=jax.default_backend(),
            device_count=jax.local_device_count(),
            model_config_json=(model.conf.to_json()
                               if hasattr(model, "conf") and
                               hasattr(model.conf, "to_json") else None),
        )
        self.storage.put_init_report(rep)
        self._initialized = True

    def iteration_done(self, model, iteration: int) -> None:
        cfg = self.config
        if not self._initialized:
            self._init_report(model)
        if iteration % max(cfg.reporting_frequency, 1) != 0:
            return
        now = time.time()
        dt_ms = (now - self._last_time) * 1000 if self._last_time else 0.0
        self._last_time = now
        rep = StatsReport(session_id=self.session_id, iteration=iteration,
                          timestamp=now)
        if cfg.collect_score:
            rep.score = float(getattr(model, "score_value", float("nan")))
        if cfg.collect_timing:
            step_s, sps = self._registry_timing(model)
            rep.iteration_time_ms = (step_s * 1e3 if step_s else dt_ms)
            if sps:
                rep.samples_per_second = sps
        if cfg.collect_memory:
            # one shared sample: the report embeds it AND the registry
            # gauges (dl4j_device_memory_bytes) pick it up
            rep.memory = _sample_device_memory(self.registry)
        if cfg.collect_histograms_params and getattr(model, "params", None):
            rep.param_histograms = _tensor_stats(model.params,
                                                 cfg.num_histogram_bins)
        if cfg.collect_mean_magnitudes and getattr(model, "params", None):
            rep.param_stats = {
                k: {"mean_magnitude": v["mean_magnitude"]}
                for k, v in (rep.param_histograms or _tensor_stats(
                    model.params, cfg.num_histogram_bins)).items()}
        if cfg.collect_introspection:
            self._collect_introspection(model, rep, iteration)
        if cfg.collect_numerics:
            self._collect_numerics(model, rep, iteration)
        self.storage.put_update(rep)

    def _collect_introspection(self, model, rep: StatsReport,
                               iteration: int) -> None:
        """Harvest the device-side introspection subtree (one batched
        transfer), extend the report, mirror the dl4j_layer_* gauges,
        and run the anomaly rules.  A model without
        ``conf.introspection`` contributes nothing."""
        from deeplearning4j_tpu.observability import introspection

        harvested = introspection.harvest_model(model)
        if harvested is None:
            return
        rep.gradient_stats = harvested["gradient_stats"]
        rep.update_stats = harvested["update_stats"]
        rep.activation_stats = harvested["activation_stats"]
        rep.replicas = harvested["replicas"]
        introspection.publish_metrics(harvested, registry=self.registry)
        if self.config.anomaly_detection:
            if self._anomaly is None:
                self._anomaly = introspection.AnomalyMonitor(
                    component=type(model).__name__)
            self._anomaly.check(harvested, iteration=iteration)

    def _collect_numerics(self, model, rep: StatsReport,
                          iteration: int) -> None:
        """Harvest the device-side precision ledger (one batched
        transfer), embed it in the report, mirror the
        ``dl4j_layer_overflow_risk`` / ``dl4j_layer_max_abs`` gauges,
        and run the format-safety rules.  A model without
        ``conf.numerics`` contributes nothing."""
        from deeplearning4j_tpu.observability import numerics

        harvested = numerics.harvest_model(model)
        if harvested is None:
            return
        rep.numerics = harvested
        numerics.publish_metrics(harvested, registry=self.registry)
        if self.config.anomaly_detection:
            if self._num_anomaly is None:
                self._num_anomaly = numerics.NumericsMonitor(
                    component=type(model).__name__)
            self._num_anomaly.check(harvested, iteration=iteration)


class HistogramIterationListener(StatsListener):
    """Weight-histogram collection shorthand.
    ≙ ``ui/weights/HistogramIterationListener.java``."""

    def __init__(self, storage, frequency: int = 1):
        super().__init__(storage, config=StatsUpdateConfiguration(
            reporting_frequency=frequency,
            collect_histograms_params=True,
            collect_memory=False))


class FlowIterationListener(IterationListener):
    """Model-structure snapshot (layer DAG + per-layer param counts) —
    feeds the flow view.  ≙ ``ui/flow/FlowIterationListener.java``."""

    def __init__(self, storage, session_id: Optional[str] = None,
                 frequency: int = 10):
        self.storage = storage
        self.session_id = session_id or _new_session_id("flow")
        self.frequency = frequency

    def iteration_done(self, model, iteration: int) -> None:
        if iteration % max(self.frequency, 1) != 0:
            return
        layers = []
        if hasattr(model, "layers"):
            for l in model.layers:
                layers.append({
                    "name": l.name,
                    "type": type(l).__name__,
                    "params": int(sum(int(np.prod(p.shape))
                                      for p in model.params.get(l.name, {}).values())),
                })
        self.storage.put_update(StatsReport(
            session_id=self.session_id, iteration=iteration,
            timestamp=time.time(),
            param_stats={"flow": {"layers": layers}}))
