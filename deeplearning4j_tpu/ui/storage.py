"""Stats persistence + routing.

Reference: ``deeplearning4j-ui-model/.../storage/{StatsStorage,
StatsStorageRouter,Persistable}.java`` and ``storage/mapdb/MapDBStatsStorage
.java`` — pluggable session stores with attach/listener fan-out.

The MapDB file store becomes a JSONL append file (self-describing records,
no native lib); in-memory store for tests/local UI.  ``FileStatsStorage``
is crash-safe: every committed report is flushed+fsynced, and a torn
trailing record (killed writer) is skipped and truncated on reload with a
warning instead of ``json.JSONDecodeError`` losing the whole history.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import threading
from collections import defaultdict
from typing import Callable, Dict, List, Optional

from deeplearning4j_tpu.ui.stats import StatsInitializationReport, StatsReport

logger = logging.getLogger("deeplearning4j_tpu.ui")

_INIT_FIELDS = {f.name for f in dataclasses.fields(StatsInitializationReport)}


class StatsStorage:
    """≙ ``storage/StatsStorage.java`` (router+query surface)."""

    def __init__(self):
        self._listeners: List[Callable[[StatsReport], None]] = []
        self._lock = threading.Lock()

    # -- router surface
    def put_init_report(self, rep: StatsInitializationReport) -> None:
        raise NotImplementedError

    def put_update(self, rep: StatsReport) -> None:
        raise NotImplementedError

    def add_listener(self, fn: Callable[[StatsReport], None]) -> None:
        with self._lock:
            self._listeners.append(fn)

    def remove_listener(self, fn: Callable[[StatsReport], None]) -> None:
        with self._lock:
            try:
                self._listeners.remove(fn)
            except ValueError:
                pass

    def _notify(self, rep: StatsReport) -> None:
        with self._lock:
            listeners = list(self._listeners)
        for fn in listeners:
            fn(rep)

    # -- query surface
    def list_session_ids(self) -> List[str]:
        raise NotImplementedError

    def get_init_report(self, session_id: str) -> Optional[StatsInitializationReport]:
        raise NotImplementedError

    def get_updates(self, session_id: str) -> List[StatsReport]:
        raise NotImplementedError

    def get_latest_update(self, session_id: str) -> Optional[StatsReport]:
        ups = self.get_updates(session_id)
        return ups[-1] if ups else None


class InMemoryStatsStorage(StatsStorage):
    """≙ ``storage/InMemoryStatsStorage.java``."""

    def __init__(self):
        super().__init__()
        self._inits: Dict[str, StatsInitializationReport] = {}
        self._updates: Dict[str, List[StatsReport]] = defaultdict(list)

    def put_init_report(self, rep) -> None:
        with self._lock:
            self._inits[rep.session_id] = rep

    def put_update(self, rep) -> None:
        with self._lock:
            self._updates[rep.session_id].append(rep)
        self._notify(rep)

    def list_session_ids(self) -> List[str]:
        return sorted(set(self._inits) | set(self._updates))

    def get_init_report(self, session_id):
        return self._inits.get(session_id)

    def get_updates(self, session_id) -> List[StatsReport]:
        with self._lock:
            return list(self._updates.get(session_id, []))


class FileStatsStorage(StatsStorage):
    """Append-only JSONL file store (replaces MapDB).
    ≙ ``storage/mapdb/MapDBStatsStorage.java`` role.

    Durability contract: ``put_update``/``put_init_report`` flush+fsync
    before returning, so every report a caller saw committed survives a
    crash; ``_load`` stops at the first torn/corrupt record, truncates
    the file back to the intact prefix (a new append must never glue
    onto a half-written line), and keeps everything before it."""

    def __init__(self, path: str):
        super().__init__()
        self.path = path
        self._mem = InMemoryStatsStorage()
        if os.path.exists(path):
            self._load()

    def _load(self) -> None:
        with open(self.path, "rb") as f:
            data = f.read()
        ok_bytes = 0
        repair_newline = False
        dropped = None
        lines = data.split(b"\n")
        for i, raw in enumerate(lines):
            terminated = i < len(lines) - 1
            line = raw.strip()
            if not line:
                if terminated:
                    ok_bytes += len(raw) + 1
                continue
            try:
                d = json.loads(line.decode("utf-8"))
                if not isinstance(d, dict):
                    raise ValueError(f"record is {type(d).__name__}, "
                                     "not an object")
            except Exception as e:
                dropped = f"line {i + 1}: {e}"
                break
            kind = d.pop("type", "update")
            if kind == "init":
                self._mem.put_init_report(StatsInitializationReport(
                    **{k: v for k, v in d.items() if k in _INIT_FIELDS}))
            else:
                self._mem.put_update(StatsReport.from_dict(d))
            if terminated:
                ok_bytes += len(raw) + 1
            else:
                # complete JSON without its trailing newline: the record
                # committed but the newline write was cut — keep it and
                # repair the terminator so the next append stays valid
                ok_bytes += len(raw)
                repair_newline = True
        if dropped is not None:
            logger.warning(
                "FileStatsStorage %s: dropping torn/corrupt tail (%s); "
                "keeping the %d intact byte(s) before it",
                self.path, dropped, ok_bytes)
        if ok_bytes < len(data):
            with open(self.path, "r+b") as f:
                f.truncate(ok_bytes)
                f.flush()
                os.fsync(f.fileno())
        if repair_newline:
            with open(self.path, "ab") as f:
                f.write(b"\n")
                f.flush()
                os.fsync(f.fileno())

    def _append(self, json_line: str) -> None:
        with self._lock:
            with open(self.path, "a", encoding="utf-8") as f:
                f.write(json_line + "\n")
                f.flush()
                # committed-means-durable: a report the caller saw
                # accepted must survive a crashed writer process
                os.fsync(f.fileno())

    def put_init_report(self, rep) -> None:
        self._mem.put_init_report(rep)
        self._append(rep.to_json())

    def put_update(self, rep) -> None:
        self._mem.put_update(rep)
        self._append(rep.to_json())
        self._notify(rep)

    def list_session_ids(self):
        return self._mem.list_session_ids()

    def get_init_report(self, session_id):
        return self._mem.get_init_report(session_id)

    def get_updates(self, session_id):
        return self._mem.get_updates(session_id)
