"""Stats persistence + routing.

Reference: ``deeplearning4j-ui-model/.../storage/{StatsStorage,
StatsStorageRouter,Persistable}.java`` and ``storage/mapdb/MapDBStatsStorage
.java`` — pluggable session stores with attach/listener fan-out.

The MapDB file store becomes a JSONL append file (self-describing records,
no native lib); in-memory store for tests/local UI.
"""

from __future__ import annotations

import json
import os
import threading
from collections import defaultdict
from typing import Callable, Dict, List, Optional

from deeplearning4j_tpu.ui.stats import StatsInitializationReport, StatsReport


class StatsStorage:
    """≙ ``storage/StatsStorage.java`` (router+query surface)."""

    def __init__(self):
        self._listeners: List[Callable[[StatsReport], None]] = []
        self._lock = threading.Lock()

    # -- router surface
    def put_init_report(self, rep: StatsInitializationReport) -> None:
        raise NotImplementedError

    def put_update(self, rep: StatsReport) -> None:
        raise NotImplementedError

    def add_listener(self, fn: Callable[[StatsReport], None]) -> None:
        self._listeners.append(fn)

    def _notify(self, rep: StatsReport) -> None:
        for fn in self._listeners:
            fn(rep)

    # -- query surface
    def list_session_ids(self) -> List[str]:
        raise NotImplementedError

    def get_init_report(self, session_id: str) -> Optional[StatsInitializationReport]:
        raise NotImplementedError

    def get_updates(self, session_id: str) -> List[StatsReport]:
        raise NotImplementedError

    def get_latest_update(self, session_id: str) -> Optional[StatsReport]:
        ups = self.get_updates(session_id)
        return ups[-1] if ups else None


class InMemoryStatsStorage(StatsStorage):
    """≙ ``storage/InMemoryStatsStorage.java``."""

    def __init__(self):
        super().__init__()
        self._inits: Dict[str, StatsInitializationReport] = {}
        self._updates: Dict[str, List[StatsReport]] = defaultdict(list)

    def put_init_report(self, rep) -> None:
        with self._lock:
            self._inits[rep.session_id] = rep

    def put_update(self, rep) -> None:
        with self._lock:
            self._updates[rep.session_id].append(rep)
        self._notify(rep)

    def list_session_ids(self) -> List[str]:
        return sorted(set(self._inits) | set(self._updates))

    def get_init_report(self, session_id):
        return self._inits.get(session_id)

    def get_updates(self, session_id) -> List[StatsReport]:
        return list(self._updates.get(session_id, []))


class FileStatsStorage(StatsStorage):
    """Append-only JSONL file store (replaces MapDB).
    ≙ ``storage/mapdb/MapDBStatsStorage.java`` role."""

    def __init__(self, path: str):
        super().__init__()
        self.path = path
        self._mem = InMemoryStatsStorage()
        if os.path.exists(path):
            self._load()

    def _load(self) -> None:
        with open(self.path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                d = json.loads(line)
                kind = d.pop("type", "update")
                if kind == "init":
                    self._mem.put_init_report(StatsInitializationReport(**d))
                else:
                    self._mem.put_update(StatsReport(**d))

    def _append(self, json_line: str) -> None:
        with self._lock:
            with open(self.path, "a", encoding="utf-8") as f:
                f.write(json_line + "\n")

    def put_init_report(self, rep) -> None:
        self._mem.put_init_report(rep)
        self._append(rep.to_json())

    def put_update(self, rep) -> None:
        self._mem.put_update(rep)
        self._append(rep.to_json())
        self._notify(rep)

    def list_session_ids(self):
        return self._mem.list_session_ids()

    def get_init_report(self, session_id):
        return self._mem.get_init_report(session_id)

    def get_updates(self, session_id):
        return self._mem.get_updates(session_id)
