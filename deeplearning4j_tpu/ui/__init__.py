"""Observability / UI (≙ deeplearning4j-ui-parent): stats listeners, stats
storage, declarative UI components, and an HTTP training dashboard."""

from deeplearning4j_tpu.ui.components import (
    ChartHistogram,
    ChartLine,
    ChartScatter,
    ChartStackedArea,
    Component,
    ComponentDiv,
    ComponentTable,
    ComponentText,
    StyleChart,
    component_from_dict,
)
from deeplearning4j_tpu.ui.render import (
    ConvolutionalIterationListener, activation_grid, write_png,
)
from deeplearning4j_tpu.ui.server import RemoteStatsListener, UIServer
from deeplearning4j_tpu.ui.stats import (
    FlowIterationListener,
    HistogramIterationListener,
    StatsInitializationReport,
    StatsListener,
    StatsReport,
    StatsUpdateConfiguration,
    device_memory_stats,
)
from deeplearning4j_tpu.ui.storage import (
    FileStatsStorage,
    InMemoryStatsStorage,
    StatsStorage,
)

__all__ = [
    "ChartHistogram", "ChartLine", "ChartScatter", "ChartStackedArea",
    "Component", "ComponentDiv", "ComponentTable", "ComponentText",
    "StyleChart", "component_from_dict", "RemoteStatsListener", "UIServer",
    "FlowIterationListener", "HistogramIterationListener",
    "StatsInitializationReport", "StatsListener", "StatsReport",
    "StatsUpdateConfiguration", "device_memory_stats", "FileStatsStorage",
    "InMemoryStatsStorage", "StatsStorage",
]
