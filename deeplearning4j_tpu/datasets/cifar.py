"""CIFAR-10 fetcher + iterator.

Reference: ``deeplearning4j-core/.../datasets/iterator/impl/CifarDataSetIterator.java``
(+ ``CifarLoader``): downloads the CIFAR-10 binary archive and parses the
``data_batch_N.bin`` record format (1 label byte + 3072 RGB bytes per
record).  No network egress here, so:
 1. parse real binary batches from ``DL4J_TPU_CIFAR_DIR`` (or
    ``~/.deeplearning4j_tpu/cifar10``) when present;
 2. otherwise generate a deterministic synthetic CIFAR-shaped dataset
    (class-colored geometric patterns + noise), flagged ``is_synthetic``.

Features come out flat [n, 3072] in CHW order like the reference loader;
use ``InputType.convolutional_flat(32, 32, 3)`` for conv nets.
"""

from __future__ import annotations

import logging
import os
from pathlib import Path
from typing import Optional, Tuple

import numpy as np

_log = logging.getLogger(__name__)

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import ListDataSetIterator

NUM_CLASSES = 10
RECORD_BYTES = 1 + 3072

_TRAIN_FILES = [f"data_batch_{i}.bin" for i in range(1, 6)]
_TEST_FILES = ["test_batch.bin"]


def _parse_batch_file(path: Path) -> Tuple[np.ndarray, np.ndarray]:
    raw = np.frombuffer(path.read_bytes(), np.uint8)
    n = len(raw) // RECORD_BYTES
    recs = raw[: n * RECORD_BYTES].reshape(n, RECORD_BYTES)
    labels = recs[:, 0].astype(np.int64)
    images = recs[:, 1:].astype(np.float32) / 255.0  # CHW flat, like CifarLoader
    return images, labels


def write_cifar_batch(path, images_u8: np.ndarray, labels: np.ndarray) -> None:
    """Format inverse of ``_parse_batch_file`` — writes the CIFAR-10 binary
    batch record layout (1 label byte + 3072 CHW RGB bytes per record) so
    the REAL parse branch can be exercised hermetically (no egress; the
    same ``write_idx`` trick tests/test_mnist_idx.py uses)."""
    images_u8 = np.asarray(images_u8, np.uint8).reshape(len(images_u8), 3072)
    labels = np.asarray(labels, np.uint8).reshape(-1, 1)
    if len(images_u8) != len(labels):
        raise ValueError(f"{len(images_u8)} images vs {len(labels)} labels")
    Path(path).write_bytes(
        np.concatenate([labels, images_u8], axis=1).tobytes())


def _find_dir(data_dir: Optional[str]) -> Path:
    return Path(data_dir or os.environ.get(
        "DL4J_TPU_CIFAR_DIR", Path.home() / ".deeplearning4j_tpu" / "cifar10"))


def _synthetic_cifar(n: int, seed: int) -> Tuple[np.ndarray, np.ndarray]:
    """Class-dependent color gradients + per-class frequency patterns."""
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, NUM_CLASSES, n)
    yy, xx = np.meshgrid(np.linspace(0, 1, 32), np.linspace(0, 1, 32),
                         indexing="ij")
    imgs = np.zeros((n, 3, 32, 32), np.float32)
    for i, c in enumerate(labels):
        phase = 2 * np.pi * c / NUM_CLASSES
        base = 0.5 + 0.5 * np.sin(2 * np.pi * (c + 1) * (xx + yy) / 4 + phase)
        imgs[i, 0] = base * (0.3 + 0.07 * c)
        imgs[i, 1] = (1 - base) * (1.0 - 0.05 * c)
        imgs[i, 2] = 0.5 + 0.5 * np.cos(2 * np.pi * (c + 1) * (xx - yy) / 4)
        imgs[i] += rng.rand(3, 32, 32).astype(np.float32) * 0.1
    return np.clip(imgs, 0, 1).reshape(n, 3072), labels


class CifarDataFetcher:
    def __init__(self, train: bool = True, data_dir: Optional[str] = None,
                 num_examples: Optional[int] = None, seed: int = 123,
                 allow_synthetic: bool = True):
        root = _find_dir(data_dir)
        names = _TRAIN_FILES if train else _TEST_FILES
        files = [root / f for f in names if (root / f).exists()]
        # also accept the extracted cifar-10-batches-bin subdir layout
        sub = root / "cifar-10-batches-bin"
        if not files and sub.exists():
            files = [sub / f for f in names if (sub / f).exists()]
        self.is_synthetic = not files
        if files:
            parts = [_parse_batch_file(f) for f in files]
            images = np.concatenate([p[0] for p in parts])
            labels = np.concatenate([p[1] for p in parts])
        else:
            if not allow_synthetic:
                raise FileNotFoundError(
                    f"CIFAR-10 binaries not found under {root}; set "
                    "DL4J_TPU_CIFAR_DIR")
            _log.warning(
                "CIFAR-10 binaries not found under %s — using SYNTHETIC "
                "class-colored patterns (is_synthetic=True). Point "
                "DL4J_TPU_CIFAR_DIR at the real batches, or pass "
                "allow_synthetic=False to fail instead.", root)
            n = num_examples or (2048 if train else 512)
            images, labels = _synthetic_cifar(n, seed if train else seed + 1)
        if num_examples is not None:
            images, labels = images[:num_examples], labels[:num_examples]
        self.features = images
        self.labels = np.eye(NUM_CLASSES, dtype=np.float32)[labels]

    def dataset(self) -> DataSet:
        return DataSet(self.features, self.labels)


class CifarDataSetIterator(ListDataSetIterator):
    def __init__(self, batch_size: int, num_examples: Optional[int] = None,
                 train: bool = True, seed: int = 123,
                 data_dir: Optional[str] = None, drop_last: bool = False):
        fetcher = CifarDataFetcher(train=train, data_dir=data_dir,
                                   num_examples=num_examples, seed=seed)
        self.is_synthetic = fetcher.is_synthetic
        super().__init__(fetcher.dataset(), batch_size, drop_last=drop_last)
