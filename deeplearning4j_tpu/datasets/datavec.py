"""DataVec bridge — record readers + record->DataSet iterators.

Reference: DataVec's ``RecordReader`` SPI wrapped by
``deeplearning4j-core/.../datasets/datavec/RecordReaderDataSetIterator.java``
(records -> DataSet with one-hot labels / regression slices) and
``SequenceRecordReaderDataSetIterator.java`` (aligned sequence readers ->
[batch, time, features] with masks for unequal lengths).

The CSV fast path parses through the native C++ core
(``deeplearning4j_tpu/native``) and falls back to Python for non-numeric
records.  Sequence padding + masking follows the framework's static-shape
discipline so downstream jit never retraces.
"""

from __future__ import annotations

from pathlib import Path
from typing import List, Optional, Sequence, Union

import numpy as np

from deeplearning4j_tpu import native
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import DataSetIterator

# Alignment modes for sequence labels (reference
# SequenceRecordReaderDataSetIterator.AlignmentMode)
ALIGN_START = "align_start"
ALIGN_END = "align_end"
EQUAL_LENGTH = "equal_length"


class RecordReader:
    """Iterates records (one example = list of float values)."""

    def next_record(self) -> List[float]:
        raise NotImplementedError

    def has_next(self) -> bool:
        raise NotImplementedError

    def reset(self) -> None:
        raise NotImplementedError


class CollectionRecordReader(RecordReader):
    """In-memory list of records (reference CollectionRecordReader)."""

    def __init__(self, records: Sequence[Sequence[float]]):
        self._records = [list(r) for r in records]
        self._pos = 0

    def next_record(self):
        r = self._records[self._pos]
        self._pos += 1
        return r

    def has_next(self):
        return self._pos < len(self._records)

    def reset(self):
        self._pos = 0


class CSVRecordReader(RecordReader):
    """CSV reader (reference CSVRecordReader): one record per line, optional
    header skip.  All-numeric files parse through the native multithreaded
    path.  ``initialize`` takes a file path (str/Path) or literal CSV
    content as ``bytes``."""

    def __init__(self, skip_lines: int = 0, delimiter: str = ","):
        self.skip_lines = skip_lines
        self.delimiter = delimiter
        self._matrix: Optional[np.ndarray] = None
        self._pos = 0

    def initialize(self, source: Union[str, Path, bytes]) -> "CSVRecordReader":
        if isinstance(source, bytes):
            data = source
        else:
            path = Path(source)
            if not path.exists():
                raise FileNotFoundError(
                    f"CSV file not found: {path} (pass literal content as "
                    "bytes)")
            data = path.read_bytes()
        self._matrix = native.csv_to_matrix(data, self.delimiter,
                                            self.skip_lines)
        self._pos = 0
        return self

    def matrix(self) -> np.ndarray:
        if self._matrix is None:
            raise RuntimeError("CSVRecordReader not initialized")
        return self._matrix

    def next_record(self):
        r = self.matrix()[self._pos]
        self._pos += 1
        return list(r)

    def has_next(self):
        return self._matrix is not None and self._pos < len(self._matrix)

    def reset(self):
        self._pos = 0


class SequenceRecordReader:
    """Iterates sequences (one example = [time, values] record list)."""

    def next_sequence(self) -> List[List[float]]:
        raise NotImplementedError

    def has_next(self) -> bool:
        raise NotImplementedError

    def reset(self) -> None:
        raise NotImplementedError


class CollectionSequenceRecordReader(SequenceRecordReader):
    def __init__(self, sequences: Sequence[Sequence[Sequence[float]]]):
        self._seqs = [[list(r) for r in s] for s in sequences]
        self._pos = 0

    def next_sequence(self):
        s = self._seqs[self._pos]
        self._pos += 1
        return s

    def has_next(self):
        return self._pos < len(self._seqs)

    def reset(self):
        self._pos = 0


class CSVSequenceRecordReader(SequenceRecordReader):
    """One CSV file per sequence (reference CSVSequenceRecordReader)."""

    def __init__(self, skip_lines: int = 0, delimiter: str = ","):
        self.skip_lines = skip_lines
        self.delimiter = delimiter
        self._files: List[Path] = []
        self._pos = 0

    def initialize(self, paths: Sequence[Union[str, Path]]
                   ) -> "CSVSequenceRecordReader":
        self._files = [Path(p) for p in paths]
        self._pos = 0
        return self

    def next_sequence(self):
        m = native.csv_to_matrix(self._files[self._pos].read_bytes(),
                                 self.delimiter, self.skip_lines)
        self._pos += 1
        return [list(r) for r in m]

    def has_next(self):
        return self._pos < len(self._files)

    def reset(self):
        self._pos = 0


def _one_hot(value: float, num_classes: int) -> np.ndarray:
    c = int(value)
    if not 0 <= c < num_classes:
        raise ValueError(f"label value {value} outside [0, {num_classes})")
    out = np.zeros(num_classes, np.float32)
    out[c] = 1.0
    return out


class RecordReaderDataSetIterator(DataSetIterator):
    """Records -> DataSet minibatches.

    Classification: ``label_index`` column becomes a one-hot label over
    ``num_classes``; remaining columns are features.  Regression
    (``regression=True``): columns [label_index, label_index_to] are the
    (raw) label vector.  ``label_index=None`` yields unlabeled features.
    """

    def __init__(self, reader: RecordReader, batch_size: int,
                 label_index: Optional[int] = None,
                 num_classes: Optional[int] = None,
                 regression: bool = False,
                 label_index_to: Optional[int] = None):
        if label_index is not None and not regression and not num_classes:
            raise ValueError("classification needs num_classes")
        self.reader = reader
        self._batch_size = batch_size
        self.label_index = label_index
        self.num_classes = num_classes
        self.regression = regression
        self.label_index_to = (label_index if label_index_to is None
                               else label_index_to)

    def _split(self, record: List[float]):
        vals = np.asarray(record, np.float32)
        if self.label_index is None:
            return vals, None
        lo, hi = self.label_index, self.label_index_to
        label_cols = vals[lo:hi + 1]
        feat = np.concatenate([vals[:lo], vals[hi + 1:]])
        if self.regression:
            return feat, label_cols
        return feat, _one_hot(label_cols[0], self.num_classes)

    def has_next(self):
        return self.reader.has_next()

    def next(self) -> DataSet:
        feats, labels = [], []
        while self.reader.has_next() and len(feats) < self._batch_size:
            f, l = self._split(self.reader.next_record())
            feats.append(f)
            if l is not None:
                labels.append(l)
        features = np.stack(feats)
        labs = (np.stack(labels) if labels
                else np.zeros((len(feats), 0), np.float32))
        return DataSet(features, labs)

    def reset(self):
        self.reader.reset()

    def batch(self):
        return self._batch_size


class SequenceRecordReaderDataSetIterator(DataSetIterator):
    """Aligned (features, labels) sequence readers -> [b, T, f] DataSets with
    masks.  Unequal feature/label lengths are aligned per ``alignment``
    (reference AlignmentMode): labels placed at the start (ALIGN_START) or
    end (ALIGN_END) of the padded time axis, masks marking validity.
    Single-reader mode splits each timestep record at ``label_index``.
    """

    def __init__(self, features_reader: SequenceRecordReader,
                 labels_reader: Optional[SequenceRecordReader] = None,
                 batch_size: int = 32,
                 num_classes: Optional[int] = None,
                 regression: bool = False,
                 label_index: Optional[int] = None,
                 alignment: str = EQUAL_LENGTH):
        self.features_reader = features_reader
        self.labels_reader = labels_reader
        self._batch_size = batch_size
        self.num_classes = num_classes
        self.regression = regression
        self.label_index = label_index
        self.alignment = alignment

    def has_next(self):
        return self.features_reader.has_next()

    def _label_array(self, rows: List[List[float]]) -> np.ndarray:
        if self.regression:
            return np.asarray(rows, np.float32)
        return np.stack([_one_hot(r[0], self.num_classes) for r in rows])

    def next(self) -> DataSet:
        fseqs, lseqs = [], []
        while (self.features_reader.has_next()
               and len(fseqs) < self._batch_size):
            fs = self.features_reader.next_sequence()
            if self.labels_reader is not None:
                ls = self.labels_reader.next_sequence()
            elif self.label_index is not None:
                li = self.label_index
                ls = [[r[li]] for r in fs]
                fs = [r[:li] + r[li + 1:] for r in fs]
            else:
                ls = None
            fseqs.append(np.asarray(fs, np.float32))
            if ls is not None:
                lseqs.append(self._label_array(ls))

        b = len(fseqs)
        T = max(max(len(s) for s in fseqs),
                max((len(s) for s in lseqs), default=0))
        nf = fseqs[0].shape[1]
        features = np.zeros((b, T, nf), np.float32)
        fmask = np.zeros((b, T), np.float32)
        for i, s in enumerate(fseqs):
            t0 = T - len(s) if self.alignment == ALIGN_END else 0
            features[i, t0:t0 + len(s)] = s
            fmask[i, t0:t0 + len(s)] = 1.0
        if not lseqs:
            return DataSet(features, np.zeros((b, T, 0), np.float32), fmask,
                           None)
        nl = lseqs[0].shape[1]
        labels = np.zeros((b, T, nl), np.float32)
        lmask = np.zeros((b, T), np.float32)
        for i, s in enumerate(lseqs):
            t0 = T - len(s) if self.alignment == ALIGN_END else 0
            labels[i, t0:t0 + len(s)] = s
            lmask[i, t0:t0 + len(s)] = 1.0
        return DataSet(features, labels, fmask, lmask)

    def reset(self):
        self.features_reader.reset()
        if self.labels_reader is not None:
            self.labels_reader.reset()

    def batch(self):
        return self._batch_size
