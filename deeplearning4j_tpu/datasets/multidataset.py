"""MultiDataSet — multi-input / multi-output minibatches for ComputationGraph.

Reference: ND4J ``MultiDataSet`` (features[], labels[] + masks) consumed by
``ComputationGraph.fit(MultiDataSetIterator)`` (``ComputationGraph.java:599``),
built from named record-reader columns by
``RecordReaderMultiDataSetIterator`` (``deeplearning4j-core/.../datavec/
RecordReaderMultiDataSetIterator.java``: builder with addInput/addOutput/
addOutputOneHot column ranges) and prefetched by
``AsyncMultiDataSetIterator`` (``deeplearning4j-nn/.../iterator/
AsyncMultiDataSetIterator.java``).

TPU redesign: arrays stay host-side numpy tuples; the CG train step moves
them to device once per step.  Inputs/outputs map positionally onto
``GraphConfiguration.inputs`` / ``.outputs`` (the reference's convention).
"""

from __future__ import annotations

import dataclasses
import queue
import threading
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np


@dataclasses.dataclass
class MultiDataSet:
    features: Tuple[np.ndarray, ...]
    labels: Tuple[np.ndarray, ...]
    features_masks: Optional[Tuple[Optional[np.ndarray], ...]] = None
    labels_masks: Optional[Tuple[Optional[np.ndarray], ...]] = None

    def __post_init__(self):
        self.features = tuple(self.features)
        self.labels = tuple(self.labels)
        if self.features_masks is not None:
            self.features_masks = tuple(self.features_masks)
        if self.labels_masks is not None:
            self.labels_masks = tuple(self.labels_masks)

    def __len__(self) -> int:
        return self.features[0].shape[0]

    def num_examples(self) -> int:
        return len(self)

    def subset(self, idx) -> "MultiDataSet":
        def _sub(arrs):
            if arrs is None:
                return None
            return tuple(None if a is None else a[idx] for a in arrs)

        return MultiDataSet(_sub(self.features), _sub(self.labels),
                            _sub(self.features_masks), _sub(self.labels_masks))

    def shuffle(self, rng: np.random.RandomState) -> "MultiDataSet":
        idx = np.arange(len(self))
        rng.shuffle(idx)
        return self.subset(idx)

    def batch_by(self, batch_size: int, drop_last: bool = False) -> List["MultiDataSet"]:
        out = []
        for i in range(0, len(self), batch_size):
            b = self.subset(slice(i, i + batch_size))
            if len(b) < batch_size and drop_last:
                continue
            out.append(b)
        return out

    @staticmethod
    def merge(sets: Sequence["MultiDataSet"]) -> "MultiDataSet":
        def _cat(pick):
            arrs = [pick(s) for s in sets]
            if arrs[0] is None:
                return None
            return tuple(
                None if any(a[i] is None for a in arrs)
                else np.concatenate([a[i] for a in arrs], 0)
                for i in range(len(arrs[0]))
            )

        return MultiDataSet(
            _cat(lambda s: s.features), _cat(lambda s: s.labels),
            _cat(lambda s: s.features_masks), _cat(lambda s: s.labels_masks),
        )


class MultiDataSetIterator:
    """Iterable over MultiDataSet minibatches with reset semantics
    (reference ``MultiDataSetIterator.java``)."""

    def __iter__(self) -> Iterator[MultiDataSet]:
        self.reset()
        return self

    def __next__(self) -> MultiDataSet:
        if not self.has_next():
            raise StopIteration
        return self.next()

    def next(self) -> MultiDataSet:
        raise NotImplementedError

    def has_next(self) -> bool:
        raise NotImplementedError

    def reset(self) -> None:
        raise NotImplementedError

    def batch(self) -> int:
        raise NotImplementedError

    def async_supported(self) -> bool:
        return True


class ListMultiDataSetIterator(MultiDataSetIterator):
    """In-memory MultiDataSet batched to fixed size."""

    def __init__(self, data: MultiDataSet, batch_size: int, drop_last: bool = False):
        self._data = data
        self._batch_size = batch_size
        self._batches = data.batch_by(batch_size, drop_last)
        self._pos = 0

    def next(self) -> MultiDataSet:
        b = self._batches[self._pos]
        self._pos += 1
        return b

    def has_next(self) -> bool:
        return self._pos < len(self._batches)

    def reset(self) -> None:
        self._pos = 0

    def batch(self) -> int:
        return self._batch_size

    def total_examples(self) -> int:
        return len(self._data)


_SENTINEL = object()


class AsyncMultiDataSetIterator(MultiDataSetIterator):
    """Background-thread prefetch with a bounded queue (reference
    ``AsyncMultiDataSetIterator.java``: blocking queue + producer thread —
    keeps host ETL off the device dispatch path)."""

    def __init__(self, underlying: MultiDataSetIterator, prefetch_size: int = 2):
        self.underlying = underlying
        self.prefetch = prefetch_size
        self._queue: "queue.Queue" = queue.Queue(maxsize=prefetch_size)
        self._thread: Optional[threading.Thread] = None
        self._next_item = _SENTINEL
        self._start()

    def _start(self):
        self._queue = queue.Queue(maxsize=self.prefetch)
        self._producer_error: Optional[BaseException] = None

        def run():
            try:
                while self.underlying.has_next():
                    self._queue.put(self.underlying.next())
            except BaseException as e:  # surface on the consumer side —
                self._producer_error = e  # never silently truncate the epoch
            finally:
                self._queue.put(_SENTINEL)

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()
        self._next_item = self._queue.get()

    def _check_error(self):
        if self._producer_error is not None:
            err, self._producer_error = self._producer_error, None
            raise RuntimeError("async prefetch producer failed") from err

    def has_next(self):
        if self._next_item is _SENTINEL:
            self._check_error()
            return False
        return True

    def next(self):
        item = self._next_item
        if item is _SENTINEL:
            self._check_error()
            raise StopIteration
        self._next_item = self._queue.get()
        return item

    def reset(self):
        if self._thread is not None and self._thread.is_alive():
            while self._next_item is not _SENTINEL:
                self._next_item = self._queue.get()
            self._thread.join(timeout=5)
        self.underlying.reset()
        self._start()

    def batch(self):
        return self.underlying.batch()


@dataclasses.dataclass(frozen=True)
class _ColumnSpec:
    reader: str
    col_from: int
    col_to: int              # inclusive, reference convention
    one_hot_classes: Optional[int] = None


class RecordReaderMultiDataSetIterator(MultiDataSetIterator):
    """Named record readers -> multi-input/-output minibatches (reference
    ``RecordReaderMultiDataSetIterator.java`` builder).  Column ranges are
    inclusive, matching the reference's ``addInput(name, from, to)``.

    Example::

        it = (RecordReaderMultiDataSetIterator.builder(batch_size=32)
              .add_reader("csv", reader)
              .add_input("csv", 0, 3)
              .add_output_one_hot("csv", 4, 3)
              .build())
    """

    def __init__(self, batch_size: int, readers, inputs, outputs):
        self._batch_size = batch_size
        self._readers = readers            # name -> RecordReader
        self._inputs: List[_ColumnSpec] = inputs
        self._outputs: List[_ColumnSpec] = outputs
        self.reset()

    class Builder:
        def __init__(self, batch_size: int):
            self._batch = batch_size
            self._readers = {}
            self._inputs: List[_ColumnSpec] = []
            self._outputs: List[_ColumnSpec] = []

        def add_reader(self, name: str, reader) -> "RecordReaderMultiDataSetIterator.Builder":
            self._readers[name] = reader
            return self

        def add_input(self, reader: str, col_from: int, col_to: int):
            self._inputs.append(_ColumnSpec(reader, col_from, col_to))
            return self

        def add_output(self, reader: str, col_from: int, col_to: int):
            self._outputs.append(_ColumnSpec(reader, col_from, col_to))
            return self

        def add_output_one_hot(self, reader: str, column: int, num_classes: int):
            self._outputs.append(
                _ColumnSpec(reader, column, column, one_hot_classes=num_classes))
            return self

        def build(self) -> "RecordReaderMultiDataSetIterator":
            for spec in self._inputs + self._outputs:
                if spec.reader not in self._readers:
                    raise ValueError(f"unknown reader '{spec.reader}'")
            if not self._inputs or not self._outputs:
                raise ValueError("need at least one input and one output spec")
            return RecordReaderMultiDataSetIterator(
                self._batch, self._readers, self._inputs, self._outputs)

    @staticmethod
    def builder(batch_size: int) -> "RecordReaderMultiDataSetIterator.Builder":
        return RecordReaderMultiDataSetIterator.Builder(batch_size)

    def reset(self):
        for r in self._readers.values():
            r.reset()
        self._done = False

    def has_next(self):
        if self._done:
            return False
        return all(r.has_next() for r in self._readers.values())

    def _collect(self, spec: _ColumnSpec, rows: dict) -> np.ndarray:
        vals = np.asarray(rows[spec.reader], np.float32)
        cols = vals[:, spec.col_from : spec.col_to + 1]
        if spec.one_hot_classes is not None:
            idx = cols[:, 0].astype(np.int64)
            return np.eye(spec.one_hot_classes, dtype=np.float32)[idx]
        return cols

    def next(self) -> MultiDataSet:
        rows = {name: [] for name in self._readers}
        for _ in range(self._batch_size):
            if not all(r.has_next() for r in self._readers.values()):
                break
            for name, r in self._readers.items():
                rows[name].append(np.asarray(r.next_record(), np.float32))
        if not any(rows.values()):
            raise StopIteration
        if not all(r.has_next() for r in self._readers.values()):
            self._done = True
        feats = tuple(self._collect(s, rows) for s in self._inputs)
        labs = tuple(self._collect(s, rows) for s in self._outputs)
        return MultiDataSet(feats, labs)

    def batch(self):
        return self._batch_size
