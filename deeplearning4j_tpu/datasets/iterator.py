"""DataSetIterator abstraction + async prefetch.

Reference: ``datasets/iterator/DataSetIterator.java`` API (next/hasNext/reset/
batch/totalExamples...), ``AsyncDataSetIterator.java:36-76`` (background
thread + LinkedBlockingQueue prefetch — the thread boundary that overlaps
host ETL with device compute), ``MultipleEpochsIterator``,
``SamplingDataSetIterator``, ``IteratorDataSetIterator``.

TPU note: prefetching matters *more* here than on the reference's CPU path —
the jitted step returns control to Python while the TPU executes, so a
prefetch thread keeps the input pipeline off the critical path.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Iterator, List, Optional

import numpy as np

logger = logging.getLogger("deeplearning4j_tpu.datasets")

from deeplearning4j_tpu.datasets.dataset import DataSet


class DataSetIterator:
    """Iterable over DataSet minibatches with reset semantics."""

    def __iter__(self) -> Iterator[DataSet]:
        self.reset()
        return self

    def __next__(self) -> DataSet:
        if not self.has_next():
            raise StopIteration
        return self.next()

    def next(self) -> DataSet:
        raise NotImplementedError

    def has_next(self) -> bool:
        raise NotImplementedError

    def reset(self) -> None:
        raise NotImplementedError

    def batch(self) -> int:
        raise NotImplementedError

    def total_examples(self) -> Optional[int]:
        return None

    def input_columns(self) -> Optional[int]:
        return None

    def total_outcomes(self) -> Optional[int]:
        return None

    def async_supported(self) -> bool:
        return True


class ListDataSetIterator(DataSetIterator):
    """In-memory list of examples batched to fixed size (reference
    ``ListDataSetIterator``)."""

    def __init__(self, data: DataSet, batch_size: int, drop_last: bool = False):
        self._data = data
        self._batch_size = batch_size
        self._drop_last = drop_last
        self._batches = data.batch_by(batch_size, drop_last)
        self._pos = 0

    def next(self) -> DataSet:
        b = self._batches[self._pos]
        self._pos += 1
        return b

    def has_next(self) -> bool:
        return self._pos < len(self._batches)

    def reset(self) -> None:
        self._pos = 0

    def batch(self) -> int:
        return self._batch_size

    def total_examples(self):
        return len(self._data)

    def input_columns(self):
        return int(np.prod(self._data.features.shape[1:]))

    def total_outcomes(self):
        return int(self._data.labels.shape[-1])


class IteratorDataSetIterator(DataSetIterator):
    """Re-batch an iterator of single examples / odd-sized DataSets into
    fixed minibatches (reference ``IteratorDataSetIterator``)."""

    def __init__(self, source, batch_size: int):
        self._source_factory = source if callable(source) else None
        self._source_list = None if callable(source) else list(source)
        self._batch_size = batch_size
        self.reset()

    def reset(self):
        src = self._source_factory() if self._source_factory else iter(self._source_list)
        self._iter = iter(src)
        self._buffer: List[DataSet] = []
        self._exhausted = False
        self._pending: Optional[DataSet] = None
        self._fill()

    def _fill(self):
        count = sum(len(d) for d in self._buffer)
        while count < self._batch_size and not self._exhausted:
            try:
                d = next(self._iter)
                self._buffer.append(d)
                count += len(d)
            except StopIteration:
                self._exhausted = True
        if self._buffer:
            merged = DataSet.merge(self._buffer) if len(self._buffer) > 1 else self._buffer[0]
            if len(merged) > self._batch_size:
                self._pending = merged.subset(slice(self._batch_size, None))
                merged = merged.subset(slice(0, self._batch_size))
            self._buffer = [merged]

    def has_next(self):
        return bool(self._buffer)

    def next(self):
        out = self._buffer.pop(0)
        if self._pending is not None:
            self._buffer = [self._pending]
            self._pending = None
            self._fill()
        else:
            self._fill()
        return out

    def batch(self):
        return self._batch_size


class MultipleEpochsIterator(DataSetIterator):
    """Replays an underlying iterator N times (reference
    ``MultipleEpochsIterator``)."""

    def __init__(self, epochs: int, underlying: DataSetIterator):
        self.epochs = epochs
        self.underlying = underlying
        self._epoch = 0

    def has_next(self):
        if self.underlying.has_next():
            return True
        if self._epoch + 1 < self.epochs:
            self._epoch += 1
            self.underlying.reset()
            return self.underlying.has_next()
        return False

    def next(self):
        return self.underlying.next()

    def reset(self):
        self._epoch = 0
        self.underlying.reset()

    def batch(self):
        return self.underlying.batch()


class SamplingDataSetIterator(DataSetIterator):
    """Draws random with-replacement minibatches (reference
    ``SamplingDataSetIterator``)."""

    def __init__(self, data: DataSet, batch_size: int, total_batches: int, seed: int = 0):
        self._data = data
        self._batch_size = batch_size
        self._total = total_batches
        self._seed = seed
        self.reset()

    def reset(self):
        self._rng = np.random.RandomState(self._seed)
        self._count = 0

    def has_next(self):
        return self._count < self._total

    def next(self):
        idx = self._rng.randint(0, len(self._data), self._batch_size)
        self._count += 1
        return self._data.subset(idx)

    def batch(self):
        return self._batch_size


class NativeBatchDataSetIterator(DataSetIterator):
    """Shuffled minibatch iterator over an in-memory DataSet, backed by the
    native C++ async pipeline (producer thread + reusable buffer pool —
    deeplearning4j_tpu/native).  The TPU-era AsyncDataSetIterator: batch
    assembly happens off the Python thread entirely; short final batches
    arrive zero-padded with a synthesized labels mask (static shapes)."""

    def __init__(self, data: DataSet, batch_size: int, shuffle: bool = True,
                 seed: int = 1, drop_last: bool = False):
        from deeplearning4j_tpu import native

        if data.features_mask is not None or data.labels_mask is not None:
            raise ValueError("masked DataSets are not supported; use "
                             "ListDataSetIterator")
        self._data = data
        self._batch_size = batch_size
        self._seed = seed
        self._resets = 0
        self._batcher = native.Batcher(data.features, data.labels, batch_size,
                                       shuffle=shuffle, seed=seed,
                                       drop_last=drop_last)
        self._pending: Optional[DataSet] = None
        self._advance()

    def _advance(self):
        out = self._batcher.next()
        if out is None:
            self._pending = None
            return
        feat, lab, n_valid = out
        lmask = None
        if n_valid < self._batch_size:
            # batch already zero-padded by the batcher; just mark valid rows
            shape = ((self._batch_size,) if lab.ndim == 2
                     else (self._batch_size, lab.shape[1]))
            lmask = np.zeros(shape, np.float32)
            lmask[:n_valid] = 1.0
        self._pending = DataSet(feat, lab, None, lmask)

    def has_next(self):
        return self._pending is not None

    def next(self):
        out = self._pending
        if out is None:
            raise StopIteration
        self._advance()
        return out

    def reset(self):
        # new permutation each epoch (deterministic given the base seed)
        self._resets += 1
        self._batcher.reset(self._seed + self._resets)
        self._advance()

    def batch(self):
        return self._batch_size

    def total_examples(self):
        return len(self._data)

    def async_supported(self):
        return False  # already asynchronous

    def close(self):
        self._batcher.close()


_SENTINEL = object()


class AsyncDataSetIterator(DataSetIterator):
    """Background-thread prefetch with a bounded queue (reference
    ``AsyncDataSetIterator.java:36-76``: LinkedBlockingQueue(prefetch) + a
    producer thread).  Wraps any DataSetIterator; ``fit`` wraps its input in
    this automatically like the reference's ``fit(DataSetIterator)`` :1032."""

    def __init__(self, underlying: DataSetIterator, prefetch_size: int = 2,
                 reset_timeout_s: float = 5.0):
        self.underlying = underlying
        self.prefetch = prefetch_size
        self.reset_timeout_s = float(reset_timeout_s)
        self._queue: "queue.Queue" = queue.Queue(maxsize=prefetch_size)
        self._thread: Optional[threading.Thread] = None
        self._next_item = _SENTINEL
        self._start()

    def _start(self):
        self._queue = queue.Queue(maxsize=self.prefetch)
        self._producer_error: Optional[BaseException] = None

        def run():
            try:
                while self.underlying.has_next():
                    self._queue.put(self.underlying.next())
            except BaseException as e:  # surface on the consumer side —
                self._producer_error = e  # never silently truncate the epoch
            finally:
                self._queue.put(_SENTINEL)

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()
        self._next_item = self._queue.get()

    def _check_error(self):
        if self._producer_error is not None:
            err, self._producer_error = self._producer_error, None
            raise RuntimeError("async prefetch producer failed") from err

    def has_next(self):
        if self._next_item is _SENTINEL:
            self._check_error()
            return False
        return True

    def next(self):
        item = self._next_item
        if item is _SENTINEL:
            self._check_error()
            raise StopIteration
        self._next_item = self._queue.get()
        return item

    def reset(self):
        if self._thread is not None and self._thread.is_alive():
            # drain (bounded) so the producer can finish, then join.  A
            # producer that makes NO progress for a whole timeout window is
            # stuck inside ``underlying.next()`` — starting a second
            # producer over the same underlying iterator would race it
            # (two threads advancing one iterator = interleaved/dropped
            # batches), so hard-fail instead of silently abandoning the
            # old thread.  Each drained item re-arms the deadline: a
            # merely SLOW producer (heavy per-batch preprocessing) gets a
            # full window per batch, not one window for the whole drain.
            deadline = time.monotonic() + self.reset_timeout_s
            while self._next_item is not _SENTINEL:
                try:
                    self._next_item = self._queue.get(
                        timeout=max(0.05, deadline - time.monotonic()))
                except queue.Empty:
                    break
                deadline = time.monotonic() + self.reset_timeout_s
            self._thread.join(timeout=max(0.05,
                                          deadline - time.monotonic()))
            if self._thread.is_alive():
                logger.error(
                    "AsyncDataSetIterator.reset: producer thread still "
                    "alive after %.1fs drain+join — refusing to start a "
                    "second producer over the same underlying iterator",
                    self.reset_timeout_s)
                raise RuntimeError(
                    "AsyncDataSetIterator.reset: prefetch producer did not "
                    f"stop within {self.reset_timeout_s}s (stuck in "
                    "underlying.next()?); a second producer would race the "
                    "live one on the underlying iterator")
        self.underlying.reset()
        self._start()

    def batch(self):
        return self.underlying.batch()
