"""LFW (Labeled Faces in the Wild) fetcher + iterator.

Reference: ``deeplearning4j-core/.../datasets/fetchers/LFWDataFetcher.java``
+ ``iterator/impl/LFWDataSetIterator.java`` (downloads the LFW archive, one
directory per person, images resized to a fixed shape, person index as the
class label).  No egress here, so:
 1. parse the reference's on-disk layout — one DIRECTORY per person under
    ``DL4J_TPU_LFW_DIR``, containing P5 PGM images (parsed natively, no
    image library), sorted person-directory index as the class label,
    nearest-neighbour resize to ``SIDE`` x ``SIDE`` — when present;
 2. else load pre-extracted ``faces.npy``/``labels.npy`` arrays;
 3. otherwise generate deterministic synthetic face-shaped images
    (elliptical head + class-dependent feature geometry), flagged
    ``is_synthetic``.
"""

from __future__ import annotations

import logging
import os
from pathlib import Path
from typing import Optional, Tuple

import numpy as np

_log = logging.getLogger(__name__)

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import ListDataSetIterator

SIDE = 40


def read_pgm(path) -> np.ndarray:
    """Parse a binary (P5) PGM image to a uint8 [H, W] array — native
    parse of a real image format, no image library (the reference decodes
    its jpgs through ImageLoader; PGM keeps the branch hermetic)."""
    raw = Path(path).read_bytes()
    fields, pos = [], 0
    while len(fields) < 4:  # magic, width, height, maxval
        if pos >= len(raw):
            raise ValueError(f"{path}: truncated PGM header")
        if raw[pos:pos + 1] == b"#":          # comment to end of line
            pos = raw.index(b"\n", pos) + 1
            continue
        if raw[pos:pos + 1].isspace():
            pos += 1
            continue
        end = pos
        while end < len(raw) and not raw[end:end + 1].isspace():
            end += 1
        fields.append(raw[pos:end])
        pos = end
    if fields[0] != b"P5":
        raise ValueError(f"{path}: not a binary P5 PGM (magic {fields[0]!r})")
    w, h, maxval = int(fields[1]), int(fields[2]), int(fields[3])
    if maxval > 255:
        raise ValueError(f"{path}: 16-bit PGM unsupported (maxval {maxval})")
    pos += 1  # single whitespace after maxval
    img = np.frombuffer(raw, np.uint8, count=w * h, offset=pos)
    return img.reshape(h, w)


def write_pgm(path, img_u8: np.ndarray) -> None:
    """Format inverse of ``read_pgm`` (binary P5) for hermetic fixtures."""
    img_u8 = np.asarray(img_u8, np.uint8)
    h, w = img_u8.shape
    Path(path).write_bytes(b"P5\n%d %d\n255\n" % (w, h) + img_u8.tobytes())


def _resize_nearest(img: np.ndarray, side: int) -> np.ndarray:
    h, w = img.shape
    ys = (np.arange(side) * h // side).clip(0, h - 1)
    xs = (np.arange(side) * w // side).clip(0, w - 1)
    return img[np.ix_(ys, xs)]


def _load_person_dirs(root: Path) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """The reference's archive layout: ``root/<person>/*.pgm``, label =
    sorted person index (LFWDataFetcher labels by directory)."""
    people = sorted(p for p in root.iterdir() if p.is_dir()
                    and any(p.glob("*.pgm")))
    if not people:
        return None
    feats, labels = [], []
    for idx, person in enumerate(people):
        for img_path in sorted(person.glob("*.pgm")):
            img = _resize_nearest(read_pgm(img_path), SIDE)
            feats.append(img.astype(np.float32).reshape(-1) / 255.0)
            labels.append(idx)
    return np.stack(feats), np.asarray(labels, np.int64)


def _synthetic_faces(n: int, num_classes: int, seed: int
                     ) -> Tuple[np.ndarray, np.ndarray]:
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, num_classes, n)
    yy, xx = np.meshgrid(np.arange(SIDE), np.arange(SIDE), indexing="ij")
    imgs = np.zeros((n, SIDE, SIDE), np.float32)
    for i, c in enumerate(labels):
        cy, cx = SIDE / 2 + rng.randn(), SIDE / 2 + rng.randn()
        ry = SIDE * (0.32 + 0.015 * (c % 5))
        rx = SIDE * (0.25 + 0.012 * (c % 7))
        head = (((yy - cy) / ry) ** 2 + ((xx - cx) / rx) ** 2) < 1.0
        img = head.astype(np.float32) * 0.6
        eye_dy, eye_dx = SIDE * 0.12, SIDE * (0.10 + 0.01 * (c % 3))
        for sx in (-1, 1):
            ey, ex = int(cy - eye_dy), int(cx + sx * eye_dx)
            img[ey - 1:ey + 2, ex - 1:ex + 2] = 1.0
        mw = int(SIDE * (0.06 + 0.01 * (c % 4)))
        my = int(cy + SIDE * 0.15)
        img[my, int(cx) - mw:int(cx) + mw + 1] = 1.0
        img += rng.rand(SIDE, SIDE).astype(np.float32) * 0.1
        imgs[i] = np.clip(img, 0, 1)
    return imgs.reshape(n, SIDE * SIDE), labels


class LFWDataFetcher:
    def __init__(self, num_examples: Optional[int] = None,
                 num_classes: int = 10, data_dir: Optional[str] = None,
                 seed: int = 123, allow_synthetic: bool = True):
        root = Path(data_dir or os.environ.get(
            "DL4J_TPU_LFW_DIR", Path.home() / ".deeplearning4j_tpu" / "lfw"))
        feats = labels = None
        if root.is_dir():
            loaded = _load_person_dirs(root)
            if loaded is not None:
                feats, labels = loaded
                num_classes = int(labels.max()) + 1
        if feats is None and (root / "faces.npy").exists() \
                and (root / "labels.npy").exists():
            feats = np.load(root / "faces.npy").astype(np.float32)
            labels = np.load(root / "labels.npy").astype(np.int64)
            feats = feats.reshape(len(feats), -1)
            num_classes = int(labels.max()) + 1
        self.is_synthetic = feats is None
        if feats is None:
            if not allow_synthetic:
                raise FileNotFoundError(
                    f"LFW arrays not found under {root}; set DL4J_TPU_LFW_DIR")
            _log.warning(
                "LFW arrays not found under %s — using SYNTHETIC faces "
                "(is_synthetic=True). Point DL4J_TPU_LFW_DIR at real data, "
                "or pass allow_synthetic=False to fail instead.", root)
            n = num_examples or 1024
            feats, labels = _synthetic_faces(n, num_classes, seed)
        if num_examples is not None:
            feats, labels = feats[:num_examples], labels[:num_examples]
        self.num_classes = num_classes
        self.features = feats
        self.labels = np.eye(num_classes, dtype=np.float32)[labels]

    def dataset(self) -> DataSet:
        return DataSet(self.features, self.labels)


class LFWDataSetIterator(ListDataSetIterator):
    def __init__(self, batch_size: int, num_examples: Optional[int] = None,
                 num_classes: int = 10, seed: int = 123,
                 data_dir: Optional[str] = None, drop_last: bool = False):
        fetcher = LFWDataFetcher(num_examples=num_examples,
                                 num_classes=num_classes, data_dir=data_dir,
                                 seed=seed)
        self.is_synthetic = fetcher.is_synthetic
        self.num_classes = fetcher.num_classes
        super().__init__(fetcher.dataset(), batch_size, drop_last=drop_last)
