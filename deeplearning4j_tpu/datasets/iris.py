"""Iris iterator (reference ``IrisDataSetIterator`` /
``datasets/fetchers/IrisDataFetcher.java``).  Data comes from sklearn's
bundled copy of the classic UCI table (no network), normalized per-column
like the reference fetcher."""

from __future__ import annotations

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import ListDataSetIterator


def iris_dataset(normalize: bool = True) -> DataSet:
    from sklearn.datasets import load_iris

    d = load_iris()
    x = d.data.astype(np.float32)
    if normalize:
        x = (x - x.mean(0)) / x.std(0)
    y = np.eye(3, dtype=np.float32)[d.target]
    return DataSet(x, y)


class IrisDataSetIterator(ListDataSetIterator):
    def __init__(self, batch_size: int = 150, num_examples: int = 150,
                 shuffle_seed: int = None):
        data = iris_dataset()
        if shuffle_seed is not None:
            data = data.shuffle(np.random.RandomState(shuffle_seed))
        data = data.subset(slice(0, num_examples))
        super().__init__(data, batch_size)
