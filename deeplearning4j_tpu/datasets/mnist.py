"""MNIST fetcher + iterator.

Reference: ``deeplearning4j-core/.../datasets/fetchers/MnistDataFetcher.java:40-84``
(downloads then parses the IDX binary files) + ``MnistManager``/
``MnistImageFile``.  This environment has no network egress, so the fetcher:
 1. parses standard IDX files from ``DL4J_TPU_MNIST_DIR`` (or
    ``~/.deeplearning4j_tpu/mnist``) when the user has them;
 2. otherwise generates a *deterministic synthetic* MNIST-shaped dataset
    (procedurally rendered digit glyphs + noise, stable across runs) so
    tests and benchmarks are hermetic.  Synthetic mode is flagged on the
    iterator (``is_synthetic``).
"""

from __future__ import annotations

import gzip
import logging
import os
import struct
from pathlib import Path
from typing import Optional, Tuple

import numpy as np

_log = logging.getLogger(__name__)

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import ListDataSetIterator

# 5x7 bitmap glyphs for digits 0-9 (classic font), used for synthetic mode.
_GLYPHS = {
    0: ["01110", "10001", "10011", "10101", "11001", "10001", "01110"],
    1: ["00100", "01100", "00100", "00100", "00100", "00100", "01110"],
    2: ["01110", "10001", "00001", "00010", "00100", "01000", "11111"],
    3: ["11111", "00010", "00100", "00010", "00001", "10001", "01110"],
    4: ["00010", "00110", "01010", "10010", "11111", "00010", "00010"],
    5: ["11111", "10000", "11110", "00001", "00001", "10001", "01110"],
    6: ["00110", "01000", "10000", "11110", "10001", "10001", "01110"],
    7: ["11111", "00001", "00010", "00100", "01000", "01000", "01000"],
    8: ["01110", "10001", "10001", "01110", "10001", "10001", "01110"],
    9: ["01110", "10001", "10001", "01111", "00001", "00010", "01100"],
}


def write_idx(path: Path, array: np.ndarray) -> None:
    """Write ``array`` as an IDX (ubyte) file — the MNIST binary layout the
    reference downloads and parses (``deeplearning4j-core/.../base/
    MnistFetcher.java:35``, binary readers ``datasets/mnist/
    MnistManager.java`` + ``MnistImageFile/MnistLabelFile``): 2 zero bytes,
    dtype code 0x08 (unsigned byte), ndim, big-endian uint32 dims, raw
    data.  A ``.gz`` suffix gzips the stream (as the reference's fetcher
    stores them).  This is the hermetic inverse of ``_read_idx`` — it lets
    tests and offline rigs exercise the REAL parse branch
    (``is_synthetic=False``) without network egress."""
    path = Path(path)
    array = np.ascontiguousarray(array, np.uint8)
    opener = gzip.open if path.suffix == ".gz" else open
    with opener(path, "wb") as f:
        f.write(struct.pack(">HBB", 0, 0x08, array.ndim))
        f.write(struct.pack(">" + "I" * array.ndim, *array.shape))
        f.write(array.tobytes())


def _read_idx(path: Path) -> np.ndarray:
    opener = gzip.open if path.suffix == ".gz" else open
    with opener(path, "rb") as f:
        zero, dtype_code, ndim = struct.unpack(">HBB", f.read(4))
        dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        data = np.frombuffer(f.read(), np.uint8)
        return data.reshape(dims)


def _find_idx_files(root: Path, train: bool) -> Optional[Tuple[Path, Path]]:
    img_names = (
        ["train-images-idx3-ubyte", "train-images.idx3-ubyte"]
        if train
        else ["t10k-images-idx3-ubyte", "t10k-images.idx3-ubyte"]
    )
    lbl_names = (
        ["train-labels-idx1-ubyte", "train-labels.idx1-ubyte"]
        if train
        else ["t10k-labels-idx1-ubyte", "t10k-labels.idx1-ubyte"]
    )
    for img in img_names:
        for suffix in ("", ".gz"):
            ip = root / (img + suffix)
            if ip.exists():
                for lbl in lbl_names:
                    lp = root / (lbl + suffix)
                    if lp.exists():
                        return ip, lp
    return None


def _synthetic_mnist(n: int, seed: int) -> Tuple[np.ndarray, np.ndarray]:
    """Deterministic MNIST-shaped data: scaled/shifted digit glyphs + noise."""
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, 10, n)
    imgs = np.zeros((n, 28, 28), np.float32)
    glyphs = {}
    for d, rows in _GLYPHS.items():
        g = np.array([[float(c) for c in r] for r in rows], np.float32)
        # upscale 5x7 -> 15x21
        glyphs[d] = np.kron(g, np.ones((3, 3), np.float32))
    for i, d in enumerate(labels):
        g = glyphs[d]
        oy = rng.randint(0, 28 - g.shape[0])
        ox = rng.randint(0, 28 - g.shape[1])
        img = np.zeros((28, 28), np.float32)
        img[oy : oy + g.shape[0], ox : ox + g.shape[1]] = g
        img += rng.rand(28, 28).astype(np.float32) * 0.15
        imgs[i] = np.clip(img, 0, 1)
    return imgs, labels


class MnistDataFetcher:
    NUM_EXAMPLES_TRAIN = 60000
    NUM_EXAMPLES_TEST = 10000

    def __init__(self, train: bool = True, data_dir: Optional[str] = None,
                 num_examples: Optional[int] = None, seed: int = 123,
                 allow_synthetic: bool = True):
        root = Path(data_dir or os.environ.get(
            "DL4J_TPU_MNIST_DIR", Path.home() / ".deeplearning4j_tpu" / "mnist"
        ))
        found = _find_idx_files(root, train) if root.exists() else None
        self.is_synthetic = found is None
        if found is not None:
            images = _read_idx(found[0]).astype(np.float32) / 255.0
            labels = _read_idx(found[1]).astype(np.int64)
        else:
            if not allow_synthetic:
                raise FileNotFoundError(
                    f"MNIST IDX files not found under {root}; set DL4J_TPU_MNIST_DIR"
                )
            _log.warning(
                "MNIST IDX files not found under %s — using deterministic "
                "SYNTHETIC digit glyphs (is_synthetic=True). Point "
                "DL4J_TPU_MNIST_DIR at real IDX files, or pass "
                "allow_synthetic=False to fail instead.", root)
            n = num_examples or (2048 if train else 512)
            images, labels = _synthetic_mnist(n, seed if train else seed + 1)
        if num_examples is not None:
            images, labels = images[:num_examples], labels[:num_examples]
        self.features = images.reshape(len(images), 784)
        self.labels = np.eye(10, dtype=np.float32)[labels]

    def dataset(self) -> DataSet:
        return DataSet(self.features, self.labels)


class MnistDataSetIterator(ListDataSetIterator):
    """Reference ``MnistDataSetIterator``: batched MNIST with one-hot labels,
    features scaled to [0,1], flat 784 vectors (use
    ``InputType.convolutional_flat(28,28,1)`` for conv nets)."""

    def __init__(self, batch_size: int, num_examples: Optional[int] = None,
                 train: bool = True, seed: int = 123, data_dir: Optional[str] = None,
                 drop_last: bool = False):
        fetcher = MnistDataFetcher(train=train, data_dir=data_dir,
                                   num_examples=num_examples, seed=seed)
        self.is_synthetic = fetcher.is_synthetic
        super().__init__(fetcher.dataset(), batch_size, drop_last=drop_last)
