from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import (
    DataSetIterator,
    ListDataSetIterator,
    AsyncDataSetIterator,
    MultipleEpochsIterator,
    SamplingDataSetIterator,
    IteratorDataSetIterator,
    NativeBatchDataSetIterator,
)
from deeplearning4j_tpu.datasets.multidataset import (
    MultiDataSet,
    MultiDataSetIterator,
    ListMultiDataSetIterator,
    AsyncMultiDataSetIterator,
    RecordReaderMultiDataSetIterator,
)
from deeplearning4j_tpu.datasets.mnist import MnistDataSetIterator
from deeplearning4j_tpu.datasets.iris import IrisDataSetIterator
from deeplearning4j_tpu.datasets.cifar import CifarDataSetIterator
from deeplearning4j_tpu.datasets.curves import CurvesDataSetIterator
from deeplearning4j_tpu.datasets.lfw import LFWDataSetIterator
from deeplearning4j_tpu.datasets.export import export_datasets, FileDataSetIterator
from deeplearning4j_tpu.datasets import datavec
