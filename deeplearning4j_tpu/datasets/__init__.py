from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import (
    DataSetIterator,
    ListDataSetIterator,
    AsyncDataSetIterator,
    MultipleEpochsIterator,
    SamplingDataSetIterator,
    IteratorDataSetIterator,
)
from deeplearning4j_tpu.datasets.mnist import MnistDataSetIterator
from deeplearning4j_tpu.datasets.iris import IrisDataSetIterator
