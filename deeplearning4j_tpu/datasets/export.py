"""Batch-and-export DataSet files + a file-backed iterator.

Reference: ``spark/data/BatchAndExportDataSetsFunction.java`` (re-batch an
RDD of DataSets and persist each minibatch as a file) and the portable
path/stream iterators (``spark/iterator/*.java``) that train directly from
those files on executors.  The binary container is the native C++ format
(``deeplearning4j_tpu/native``: 'D4JT' header + f32 payloads), so export and
re-read round-trip through native IO.
"""

from __future__ import annotations

from pathlib import Path
from typing import List, Union

import numpy as np

from deeplearning4j_tpu import native
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import DataSetIterator


def export_datasets(iterator: DataSetIterator, out_dir: Union[str, Path],
                    prefix: str = "dataset") -> List[Path]:
    """Persist every minibatch of `iterator` as `<prefix>_<i>.bin`.

    Masks (e.g. the synthesized labels mask on a zero-padded final batch)
    round-trip through an `<name>.masks.npz` sidecar so padded rows stay
    invalid after re-read."""
    out = Path(out_dir)
    out.mkdir(parents=True, exist_ok=True)
    paths = []
    iterator.reset()
    i = 0
    while iterator.has_next():
        ds = iterator.next()
        p = out / f"{prefix}_{i:05d}.bin"  # zero-padded so glob-sort == order
        native.write_dataset(p, ds.features, ds.labels)
        if ds.features_mask is not None or ds.labels_mask is not None:
            masks = {}
            if ds.features_mask is not None:
                masks["features_mask"] = ds.features_mask
            if ds.labels_mask is not None:
                masks["labels_mask"] = ds.labels_mask
            np.savez(p.with_suffix(".masks.npz"), **masks)
        paths.append(p)
        i += 1
    return paths


class FileDataSetIterator(DataSetIterator):
    """Iterates exported minibatch files in name order; shapes are restored
    flat ([batch, -1]) which matches the framework's layer input contract."""

    def __init__(self, directory: Union[str, Path], pattern: str = "*.bin"):
        self._paths = sorted(Path(directory).glob(pattern))
        if not self._paths:
            raise FileNotFoundError(f"no {pattern} files in {directory}")
        self._pos = 0

    def has_next(self):
        return self._pos < len(self._paths)

    def next(self) -> DataSet:
        path = self._paths[self._pos]
        feat, lab = native.read_dataset(path)
        self._pos += 1
        if lab is None:
            lab = np.zeros((len(feat), 0), np.float32)
        fmask = lmask = None
        sidecar = path.with_suffix(".masks.npz")
        if sidecar.exists():
            with np.load(sidecar) as z:
                fmask = z.get("features_mask")
                lmask = z.get("labels_mask")
        return DataSet(feat, lab, fmask, lmask)

    def reset(self):
        self._pos = 0

    def batch(self):
        n, _, _ = native.dataset_header(self._paths[0])
        return n
