"""DataSet container — features + labels (+ masks), host-side numpy.

Reference: ND4J ``DataSet`` (features/labels/featuresMask/labelsMask) used
throughout ``deeplearning4j-nn/.../datasets``.  Host arrays stay numpy;
device transfer happens once per step inside the jitted train function
(minimising host<->HBM traffic).  Static-shape discipline: ``pad_batch``
pads the last short minibatch so jit never retraces (SURVEY.md §7 hard-part 2).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

import numpy as np


@dataclasses.dataclass
class DataSet:
    features: np.ndarray
    labels: np.ndarray
    features_mask: Optional[np.ndarray] = None
    labels_mask: Optional[np.ndarray] = None

    def __len__(self) -> int:
        return self.features.shape[0]

    def num_examples(self) -> int:
        return len(self)

    def split_test_and_train(self, n_train: int, rng: Optional[np.random.RandomState] = None
                             ) -> Tuple["DataSet", "DataSet"]:
        idx = np.arange(len(self))
        if rng is not None:
            rng.shuffle(idx)
        tr, te = idx[:n_train], idx[n_train:]
        return self.subset(tr), self.subset(te)

    def subset(self, idx) -> "DataSet":
        return DataSet(
            self.features[idx],
            self.labels[idx],
            None if self.features_mask is None else self.features_mask[idx],
            None if self.labels_mask is None else self.labels_mask[idx],
        )

    def shuffle(self, rng: np.random.RandomState) -> "DataSet":
        idx = np.arange(len(self))
        rng.shuffle(idx)
        return self.subset(idx)

    def batch_by(self, batch_size: int, drop_last: bool = False) -> List["DataSet"]:
        out = []
        for i in range(0, len(self), batch_size):
            b = self.subset(slice(i, i + batch_size))
            if len(b) < batch_size:
                if drop_last:
                    continue
                b = b.pad_batch(batch_size)
            out.append(b)
        return out

    def pad_batch(self, batch_size: int) -> "DataSet":
        """Pad to a fixed batch size with zero rows + zero label-mask so the
        padded rows contribute nothing to masked losses, keeping shapes
        static under jit."""
        n = len(self)
        if n == batch_size:
            return self
        pad = batch_size - n

        def _pad(a):
            if a is None:
                return None
            return np.concatenate([a, np.zeros((pad,) + a.shape[1:], a.dtype)], 0)

        fm = self.features_mask
        lm = self.labels_mask
        if lm is None and self.labels.ndim >= 2:
            # synthesize a labels mask marking real rows
            shape = (batch_size,) if self.labels.ndim == 2 else (batch_size, self.labels.shape[1])
            lm = np.zeros(shape, np.float32)
            lm[:n] = 1.0
            return DataSet(_pad(self.features), _pad(self.labels), _pad(fm), lm)
        return DataSet(_pad(self.features), _pad(self.labels), _pad(fm), _pad(lm))

    def as_tuple(self):
        return (self.features, self.labels, self.features_mask, self.labels_mask)

    @staticmethod
    def merge(datasets: List["DataSet"]) -> "DataSet":
        return DataSet(
            np.concatenate([d.features for d in datasets], 0),
            np.concatenate([d.labels for d in datasets], 0),
            None if datasets[0].features_mask is None
            else np.concatenate([d.features_mask for d in datasets], 0),
            None if datasets[0].labels_mask is None
            else np.concatenate([d.labels_mask for d in datasets], 0),
        )
