"""Curves dataset — synthetic parametric curve images for pretraining tests.

Reference: ``deeplearning4j-core/.../datasets/fetchers/CurvesDataFetcher.java``
(downloads a fixed curves dataset used by the deep-autoencoder examples).
The dataset is inherently synthetic; here it is generated deterministically:
each example renders a random smooth parametric curve (random low-order
Fourier coefficients) onto a 28x28 canvas.  Unsupervised: labels == features
(autoencoder reconstruction targets), exactly how the reference uses it.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import ListDataSetIterator

SIDE = 28


def _render_curve(rng: np.random.RandomState) -> np.ndarray:
    t = np.linspace(0, 2 * np.pi, 200)
    x = np.zeros_like(t)
    y = np.zeros_like(t)
    for k in range(1, 4):
        x = x + rng.randn() / k * np.cos(k * t) + rng.randn() / k * np.sin(k * t)
        y = y + rng.randn() / k * np.cos(k * t) + rng.randn() / k * np.sin(k * t)
    # normalize into the canvas with a margin
    x = (x - x.min()) / max(np.ptp(x), 1e-6) * (SIDE - 5) + 2
    y = (y - y.min()) / max(np.ptp(y), 1e-6) * (SIDE - 5) + 2
    img = np.zeros((SIDE, SIDE), np.float32)
    img[y.astype(int), x.astype(int)] = 1.0
    return img


def curves(n: int = 1024, seed: int = 123) -> Tuple[np.ndarray, np.ndarray]:
    rng = np.random.RandomState(seed)
    feats = np.stack([_render_curve(rng).reshape(-1) for _ in range(n)])
    return feats, feats.copy()


class CurvesDataSetIterator(ListDataSetIterator):
    def __init__(self, batch_size: int, num_examples: int = 1024,
                 seed: int = 123, drop_last: bool = False):
        feats, labels = curves(num_examples, seed)
        super().__init__(DataSet(feats, labels), batch_size,
                         drop_last=drop_last)
