"""Pipeline parallelism — GPipe-style stage placement with microbatching.

Beyond-reference extension (SURVEY.md §2: PP absent in the reference).

Design: the layer stack is split into S stages balanced by parameter
count; stage s's parameters live on device s.  A global batch is cut into
M microbatches; the forward enqueues (microbatch, stage) work in schedule
order and JAX's async dispatch overlaps them — while microbatch m runs on
stage s, microbatch m+1 runs on stage s-1, exactly the GPipe fill/drain
diagram, with activation transfers riding ICI on real hardware.  The
backward replays the schedule in reverse through stored ``jax.vjp``
pullbacks, accumulating per-stage gradients on their home devices; the
updater then applies per stage with no cross-device parameter traffic.

Scope: sequential stateless nets (no BatchNorm running stats, no masks,
no TBPTT) — conv/dense/activation/attention/layernorm stacks.  Compose
with DP/TP by using those masters; this one owns the pipe axis.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.optimize import updaters as upd
from deeplearning4j_tpu.parallel.training_master import TrainingMaster


def split_stages(net, n_stages: int) -> List[List[int]]:
    """Partition layer indices into n_stages contiguous groups, balanced by
    parameter count (the reference has no analog; think layer-to-executor
    assignment)."""
    counts = []
    for layer in net.layers:
        lp = net.params.get(layer.name, {})
        counts.append(sum(int(np.prod(a.shape)) for a in lp.values()) or 1)
    n_stages = min(n_stages, len(counts))
    total = sum(counts)
    target = total / n_stages
    stages: List[List[int]] = [[]]
    acc = 0.0
    for i, c in enumerate(counts):
        layers_left = len(counts) - i          # including this one
        stages_to_open = n_stages - len(stages)
        if stages[-1]:
            # MUST open when every remaining layer is needed to fill the
            # remaining stages; MAY open when the current stage hit the
            # balance target and enough layers remain
            if layers_left <= stages_to_open or (
                    acc >= target and stages_to_open > 0
                    and layers_left >= stages_to_open):
                stages.append([])
                acc = 0.0
        stages[-1].append(i)
        acc += c
    return stages


class PipelineParallelTrainingMaster(TrainingMaster):
    def __init__(self, n_stages: Optional[int] = None,
                 n_microbatches: int = 4,
                 devices: Optional[Sequence] = None):
        self.devices = list(devices if devices is not None else jax.devices())
        self.n_stages = n_stages or len(self.devices)
        if self.n_stages > len(self.devices):
            raise ValueError(
                f"{self.n_stages} stages > {len(self.devices)} devices")
        self.n_microbatches = n_microbatches
        self._built = False

    # ------------------------------------------------------------ validation
    def _validate(self, net):
        if net.conf.backprop_type == "truncated_bptt":
            raise ValueError("pipeline master does not support TBPTT")
        for layer in net.layers:
            if layer.init_state():
                raise ValueError(
                    f"pipeline master needs stateless layers; '{layer.name}' "
                    f"({type(layer).__name__}) carries state")
            if layer.dropout > 0:
                raise ValueError("pipeline master does not support dropout")

    # ------------------------------------------------------------- stage fns
    def _build(self, net):
        self._validate(net)
        self.stages = split_stages(net, self.n_stages)
        self.stage_layers = [[net.layers[i] for i in s] for s in self.stages]
        out_layer = net.layers[-1]

        def make_stage_fwd(layers):
            def fwd(stage_params, a):
                for layer in layers:
                    if layer.has_params():
                        a, _ = layer.apply(stage_params[layer.name], {}, a,
                                           train=True, rng=None)
                    else:
                        a, _ = layer.apply({}, {}, a, train=True, rng=None)
                return a
            return fwd

        def make_last_stage(layers):
            body = layers[:-1]

            def fwd_loss(stage_params, a, y):
                for layer in body:
                    p = stage_params.get(layer.name, {})
                    a, _ = layer.apply(p, {}, a, train=True, rng=None)
                return out_layer.score(stage_params[out_layer.name], a, y)
            return fwd_loss

        self._stage_fwds = [jax.jit(make_stage_fwd(ls))
                            for ls in self.stage_layers[:-1]]
        self._last_stage = jax.jit(make_last_stage(self.stage_layers[-1]))
        self._reg_fns = [
            jax.jit(jax.value_and_grad(lambda sp, ls=ls: sum(
                layer.reg_score(sp.get(layer.name, {})) for layer in ls)))
            for ls in self.stage_layers
        ]
        cfg = net.conf.updater
        self._lr_overrides = {
            l.name: l.learning_rate for l in net.layers
            if l.learning_rate is not None
        }
        self._upd_cfg = cfg
        self._built = True

    def _stage_params(self, net, s: int) -> Dict[str, Any]:
        names = [net.layers[i].name for i in self.stages[s]]
        return {n: net.params[n] for n in names if n in net.params}

    # ---------------------------------------------------------------- train
    def execute_training(self, net, iterator):

        if not self._built:
            self._build(net)
        S = len(self.stages)
        # place each stage's params + updater state on its device
        stage_params = [
            jax.device_put(self._stage_params(net, s), self.devices[s])
            for s in range(S)
        ]
        stage_upd = [
            jax.device_put(
                {slot: {n: tree[n] for n in stage_params[s] if n in tree}
                 for slot, tree in net.updater_state.items()},
                self.devices[s])
            for s in range(S)
        ]

        for ds in iterator:
            loss = self._train_batch(net, ds, stage_params, stage_upd)
            net.score_value = float(loss)
            net.iteration += 1
            for lst in net.listeners:
                lst.iteration_done(net, net.iteration)
        # merge stage params back
        for s in range(S):
            for name, p in stage_params[s].items():
                net.params[name] = jax.device_put(p, self.devices[0])
        for slot in net.updater_state:
            merged = {}
            for s in range(S):
                merged.update(stage_upd[s][slot])
            net.updater_state[slot] = {
                n: jax.device_put(v, self.devices[0])
                for n, v in merged.items()}

    def _train_batch(self, net, ds, stage_params, stage_upd):
        if ds.features_mask is not None or ds.labels_mask is not None:
            raise ValueError("pipeline master does not support masked batches")
        S = len(self.stages)
        M = self.n_microbatches
        x = jnp.asarray(ds.features)
        y = jnp.asarray(ds.labels)
        if len(x) % M:
            raise ValueError(f"batch {len(x)} not divisible by "
                             f"{M} microbatches")
        xs = jnp.split(x, M)
        ys = jnp.split(y, M)

        # forward (fill): async dispatch overlaps (m, s) with (m+1, s-1)
        pullbacks = [[None] * S for _ in range(M)]
        losses = []
        for m in range(M):
            a = jax.device_put(xs[m], self.devices[0])
            for s in range(S - 1):
                a, vjp = jax.vjp(self._stage_fwds[s], stage_params[s], a)
                pullbacks[m][s] = vjp
                a = jax.device_put(a, self.devices[s + 1])
            y_m = jax.device_put(ys[m], self.devices[S - 1])
            loss_m, vjp = jax.vjp(self._last_stage, stage_params[S - 1], a,
                                  y_m)
            pullbacks[m][S - 1] = vjp
            losses.append(loss_m)

        # backward (drain), reverse schedule; grads accumulate per stage
        grads = [None] * S
        for m in range(M):
            seed = jnp.ones((), losses[m].dtype) / M
            gp, ga, _gy = pullbacks[m][S - 1](seed)
            grads[S - 1] = gp if grads[S - 1] is None else jax.tree_util.tree_map(
                jnp.add, grads[S - 1], gp)
            for s in range(S - 2, -1, -1):
                ga = jax.device_put(ga, self.devices[s])
                gp, ga = pullbacks[m][s](ga)
                grads[s] = gp if grads[s] is None else jax.tree_util.tree_map(
                    jnp.add, grads[s], gp)

        # regularization value+gradients + updater apply, per stage on-device
        it = jnp.asarray(float(net.iteration))
        reg_vals = []
        for s in range(S):
            reg_val, reg_grad = self._reg_fns[s](stage_params[s])
            reg_vals.append(reg_val)  # no host sync inside the dispatch loop
            g = jax.tree_util.tree_map(jnp.add, grads[s], reg_grad)
            updates, stage_upd[s] = upd.update(
                self._upd_cfg, g, stage_upd[s], it, self._lr_overrides)
            stage_params[s] = {
                ln: (upd.apply_updates(stage_params[s][ln], u)
                     if (u := updates.get(ln)) else stage_params[s][ln])
                for ln in stage_params[s]
            }
        # score matches serial _loss_fn: data loss + regularization penalty
        return (sum(jax.device_get(l) for l in losses) / M
                + sum(float(r) for r in reg_vals))
